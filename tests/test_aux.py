"""Aux-subsystem tests: distributed helpers, profiling, checkpoint/resume,
data-generator CLI."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from matvec_mpi_multiplier_tpu import make_mesh
from matvec_mpi_multiplier_tpu.bench.profiling import annotate, trace
from matvec_mpi_multiplier_tpu.models import trainer
from matvec_mpi_multiplier_tpu.parallel import distributed
from matvec_mpi_multiplier_tpu.utils import checkpoint


def test_distributed_single_process(devices):
    # Single-host: trivial identities, no initialization needed.
    assert distributed.process_count() == 1
    assert distributed.process_index() == 0
    assert distributed.is_main_process()
    assert distributed.device_count() == 8
    assert distributed.local_device_count() == 8
    distributed.initialize()  # must be a no-op, not raise
    assert distributed.process_count() == 1


def test_max_across_processes_multiprocess_fake(devices, monkeypatch):
    # The multi-host max-reduce (MPI_Reduce(MPI_MAX) analog,
    # src/multiplier_rowwise.c:147) cannot run for real on a single host;
    # pin its semantics behind fakes: with process_count>1 it must return the
    # max over the allgathered per-process values, not the local one.
    from matvec_mpi_multiplier_tpu.bench import timing

    monkeypatch.setattr(jax, "process_count", lambda: 4)
    from jax.experimental import multihost_utils

    gathered = []

    def fake_allgather(value):
        gathered.append(float(value))
        return np.array([0.25, 0.75, float(value), 0.5])

    monkeypatch.setattr(multihost_utils, "process_allgather", fake_allgather)
    assert timing._max_across_processes(0.1) == 0.75  # remote rank is slowest
    assert timing._max_across_processes(0.9) == 0.9   # local rank is slowest
    assert gathered == [0.1, 0.9]  # the local value entered the allgather


def test_initialize_multiprocess_fakes(devices, monkeypatch):
    # initialize() semantics behind fakes (jax.distributed.initialize must
    # not actually run in tests):
    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )

    # 1. Already initialized (process_count > 1): no second init — the
    #    reference's MPI_Init is likewise once-only (src/multiplier_rowwise.c:66).
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    distributed.initialize(coordinator_address="h:1", num_processes=2)
    assert calls == []

    # 2. Explicit coordinates: passed through verbatim.
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    distributed.initialize(
        coordinator_address="host:1234", num_processes=4, process_id=3
    )
    assert calls == [
        {
            "coordinator_address": "host:1234",
            "num_processes": 4,
            "process_id": 3,
        }
    ]

    # 3. No coordinates, launcher env present (SLURM): autodetect path.
    calls.clear()
    monkeypatch.setenv("SLURM_JOB_ID", "42")
    distributed.initialize()
    assert calls == [{}]


def test_is_main_process_multiprocess_fake(devices, monkeypatch):
    # Rank-role check on a faked non-zero rank (rank == MAIN_PROCESS is the
    # reference's coordinator convention, src/constants.h:5).
    monkeypatch.setattr(jax, "process_index", lambda: 3)
    assert not distributed.is_main_process()
    assert distributed.process_index() == 3


def test_tpu_measure_all_stage_plumbing(monkeypatch):
    # The capture script must abort before any stage when the probe fails,
    # and run stages cheapest-first when it succeeds (mocked subprocesses —
    # the real accelerator path can't run in tests).
    from pathlib import Path

    monkeypatch.syspath_prepend(str(Path(__file__).parents[1] / "scripts"))
    import tpu_measure_all

    monkeypatch.setattr(tpu_measure_all, "probe", lambda **kw: False)
    assert tpu_measure_all.main(["--data-root", "x"]) == 1

    calls = []
    monkeypatch.setattr(tpu_measure_all, "probe", lambda **kw: True)
    monkeypatch.setattr(
        tpu_measure_all, "run", lambda cmd: calls.append(cmd) or 0
    )
    # Pin the stage decision: nbconvert lives in the [analysis] extra, so a
    # [test]-only environment would silently skip the stage and fail the
    # order assertions below for the wrong reason.
    monkeypatch.setattr(tpu_measure_all, "_has_nbconvert", lambda: True)
    # _baseline_stage spawns its subprocess directly (not via run); stub it
    # with a marker so its position in the order is still pinned.
    monkeypatch.setattr(
        tpu_measure_all, "_baseline_stage",
        lambda py: calls.append(["BASELINE-STAGE"]) or 0,
    )
    # Pin the overlay decision: the real hook checks /root/reference,
    # which only exists on the capture host.
    monkeypatch.setattr(
        tpu_measure_all, "_reference_out", lambda: Path("/ref/out")
    )
    # Default data root (all subprocesses are stubbed, nothing touches
    # data/): the notebook stage only fires for the default root.
    rc = tpu_measure_all.main([])
    assert rc == 0
    joined = [" ".join(c) for c in calls]

    def stage(substr):
        hits = [i for i, c in enumerate(joined) if substr in c]
        assert hits, f"stage {substr!r} never ran"
        return hits[0]

    # Highest-leverage-first ORDER is the wedge-safety property: a mid-run
    # wedge must only lose the later, cheaper-to-lose stages. The 65536^2
    # north-star runs right after the cheap headline — a wedge mid-sweep
    # must never cost it again. After the square sweep (the core dataset
    # deliverable), the cheap one-shot evidence stages (gemm tiers,
    # compensated, both non-attention autotunes) run BEFORE the long
    # asymmetric sweep: healthy windows can be minutes, and the sweeps
    # resume via --skip-measured so they lose nothing by going later.
    # The sweeps run as separate invocations so each gets its own stage
    # budget, and the sub-VMEM roof re-derives after each sweep.
    assert (
        stage("bench.py") < stage("BASELINE-STAGE")
        < stage("--sweep square")
        # The measured sub-VMEM ceiling derives from the sweep CSVs just
        # written, so its stage must directly follow the square sweep.
        < stage("derive_vmem_roof")
        < stage("--op gemm") < stage("compensated_study")
        # The roofline-knee study rides the same warm MXU window as the
        # GEMM/compensated tiers it contextualizes.
        < stage("crossover_study")
        < stage("autotune_pallas.py") < stage("autotune_pallas_gemm.py")
        < stage("--sweep asymmetric") < stage("hostlink_study")
        < stage("overlap_study")
    )
    # The roof re-derives IMMEDIATELY after the asymmetric sweep folds in
    # its own sub-VMEM rows — before any downstream consumer (hostlink
    # onward, and ultimately the figures stage and the data-quality
    # gates) reads it.
    roof_runs = [i for i, c in enumerate(joined) if "derive_vmem_roof" in c]
    assert len(roof_runs) == 2
    assert stage("--sweep asymmetric") < roof_runs[1] < stage("hostlink_study")
    # The fp64-parity GEMM tier's on-chip cost lands with the capture.
    assert any("--kernel ozaki" in c for c in joined)
    # Every sweep-family stage resumes over rows an earlier wedge-killed
    # attempt already flushed (the once-per-round wipe sentinel guarantees
    # surviving rows are this round's own).
    for c in joined:
        if "bench.sweep" in c:
            assert "--skip-measured" in c, c
    # The attention tile autotune runs after the GEMM one, on the SAME
    # causal workload the attention stage measures (a non-causal tune
    # could crown the wrong tile for the workload actually reported).
    att_tune = stage("autotune_pallas_attention.py")
    assert stage("autotune_pallas_gemm.py") < att_tune
    assert "--causal" in joined[att_tune]

    # The notebook re-execution is LAST (it renders whatever dataset the
    # earlier stages finished writing)...
    assert stage("stats_visualization.py") < stage("nbconvert")
    # The figures stage overlays this framework's curves over the
    # reference's committed MPI curves (VERDICT round-4 item 5) — pinned
    # via the _reference_out hook so the assertion holds on hosts without
    # the reference mount too (the stage must degrade gracefully there,
    # checked below).
    fig_call = joined[stage("stats_visualization.py")]
    assert "--overlay" in fig_call
    assert "reference=/ref/out" in fig_call
    assert stage("nbconvert") == len(joined) - 1
    # ...and only runs against the default data root — the notebook reads
    # the committed data/out, so a custom-root capture must not refresh its
    # outputs over a dataset it did not read.
    calls.clear()
    assert tpu_measure_all.main(["--data-root", "other"]) == 0
    assert not any("nbconvert" in " ".join(c) for c in calls)

    # Without the reference mount the figures stage degrades to the plain
    # per-strategy/roofline figures instead of dying in the overlay loop.
    calls.clear()
    monkeypatch.setattr(tpu_measure_all, "_reference_out", lambda: None)
    assert tpu_measure_all.main([]) == 0
    fig_calls = [c for c in (" ".join(x) for x in calls)
                 if "stats_visualization.py" in c]
    assert fig_calls and "--overlay" not in fig_calls[0]

    # --skip must actually suppress a stage (the baseline is 8.6 GB of
    # operands — a mis-spelled skip key silently running it would be costly).
    calls.clear()
    assert tpu_measure_all.main(["--data-root", "x", "--skip", "baseline"]) == 0
    assert not any("BASELINE-STAGE" in " ".join(c) for c in calls)
    assert any("--sweep square" in " ".join(c) for c in calls)


def test_tpu_measure_all_soft_vs_hard_rc(monkeypatch, capsys):
    """Sweep rc=3 (completed, only unmeasurable skips) must NOT fail the
    capture — the watcher would otherwise re-run the whole thing over rows a
    retry cannot improve. Sweep rc=1 (completed with transient config
    failures) makes the CAPTURE retryable: --skip-measured means the retry
    redoes only the failed configs, so stopping the watcher over a tunnel
    hiccup would forfeit every later window. rc=2 from ANY stage (argparse
    usage-error convention, even a sweep) and rc=1 from non-sweep stages
    stay deterministic-hard."""
    from pathlib import Path

    monkeypatch.syspath_prepend(str(Path(__file__).parents[1] / "scripts"))
    import tpu_measure_all

    monkeypatch.setattr(tpu_measure_all, "probe", lambda **kw: True)
    monkeypatch.setattr(tpu_measure_all, "_baseline_stage", lambda py: 0)
    monkeypatch.setattr(tpu_measure_all, "_has_nbconvert", lambda: False)

    def rc_for(cmd):
        return 3 if "--sweep" in " ".join(cmd) else 0

    monkeypatch.setattr(tpu_measure_all, "run", rc_for)
    assert tpu_measure_all.main(["--data-root", "x"]) == 0
    out = capsys.readouterr().out
    assert "soft-skip" in out and "0 hard-failed" in out

    # A sweep that completed but hard-failed some configs (transient
    # tunnel faults under --keep-going; sweep exit 5) is the RETRYABLE
    # class: the capture exits 1 so the watcher tries the next window.
    monkeypatch.setattr(
        tpu_measure_all, "run",
        lambda cmd: 5 if "--sweep asymmetric" in " ".join(cmd) else 0,
    )
    assert tpu_measure_all.main(["--data-root", "x"]) == 1
    out = capsys.readouterr().out
    assert "retryable" in out
    # Consistent report: a retryable stage is tagged RETRY, never FAILED,
    # and never counted in the hard-failed summary.
    assert "RETRY" in out and "FAILED" not in out
    assert "0 hard-failed" in out

    # ...even when a deterministic stage failure coexists: the retry
    # re-fails that stage cheaply, and once the sweeps complete the
    # deterministic failure alone stops the loop.
    monkeypatch.setattr(
        tpu_measure_all, "run",
        lambda cmd: 5 if "--sweep asymmetric" in " ".join(cmd)
        else (1 if "overlap_study" in " ".join(cmd) else 0),
    )
    assert tpu_measure_all.main(["--data-root", "x"]) == 1

    # A sweep CRASH (exit 1 — config bug, re-raised MatvecError) is NOT
    # the retryable class: deterministic, capture exits 4.
    monkeypatch.setattr(
        tpu_measure_all, "run",
        lambda cmd: 1 if "--sweep asymmetric" in " ".join(cmd) else 0,
    )
    assert tpu_measure_all.main(["--data-root", "x"]) == 4

    # The baseline stage's rc=1 (cpu-fallback / no JSON — the tunnel
    # wedging between probe and stage) is retryable: the north star must
    # never be forfeited over a transient.
    monkeypatch.setattr(tpu_measure_all, "run", lambda cmd: 0)
    monkeypatch.setattr(tpu_measure_all, "_baseline_stage", lambda py: 1)
    assert tpu_measure_all.main(["--data-root", "x"]) == 1
    monkeypatch.setattr(tpu_measure_all, "_baseline_stage", lambda py: 0)

    # argparse's usage-error exit (2) from a sweep stage must stay hard: a
    # broken sweep command line writes zero rows, and "capture succeeded"
    # over that would waste the healthy window without anyone noticing.
    # Hard failures in a COMPLETED run exit 4 (deterministic — the watcher
    # must not endlessly re-run the capture), distinct from the retryable
    # wedge-abort rc 1.
    monkeypatch.setattr(
        tpu_measure_all, "run",
        lambda cmd: 2 if "--sweep" in " ".join(cmd) else 0,
    )
    assert tpu_measure_all.main(["--data-root", "x"]) == 4

    # An overlap-stage crash (rc=1) is a hard failure too...
    monkeypatch.setattr(
        tpu_measure_all, "run",
        lambda cmd: 1 if "overlap_study" in " ".join(cmd) else 0,
    )
    assert tpu_measure_all.main(["--data-root", "x"]) == 4
    assert "overlap" in capsys.readouterr().out

    # ...and so is rc=2 from a non-sweep stage (argparse usage error: a
    # retry is pointless, but "capture succeeded" would be a lie).
    monkeypatch.setattr(
        tpu_measure_all, "run",
        lambda cmd: 2 if "hostlink_study" in " ".join(cmd) else 0,
    )
    assert tpu_measure_all.main(["--data-root", "x"]) == 4

    # A mid-run WEDGE (stage timeout) stays rc 1 — the retryable class.
    def wedge(cmd):
        raise tpu_measure_all.StageWedged("stage exceeded budget")

    monkeypatch.setattr(tpu_measure_all, "run", wedge)
    assert tpu_measure_all.main(["--data-root", "x"]) == 1


def test_autotune_gemv_cli_smoke(monkeypatch, tmp_path):
    """End-to-end plumbing of the GEMV tile autotuner on the CPU backend:
    interpret-mode candidates, report generation, winner line."""
    from pathlib import Path

    monkeypatch.syspath_prepend(str(Path(__file__).parents[1] / "scripts"))
    import autotune_pallas

    monkeypatch.setattr(autotune_pallas, "BMS", (64,))
    monkeypatch.setattr(autotune_pallas, "BKS", (128,))
    report = tmp_path / "AUTOTUNE.md"
    rc = autotune_pallas.main([
        "--platform", "cpu", "--allow-interpret", "--size", "128",
        "--n-reps", "1", "--samples", "1", "--report", str(report),
    ])
    assert rc == 0
    text = report.read_text()
    assert "pallas 64x128" in text
    assert "Best tile" in text


def test_autotune_gemm_cli_smoke(monkeypatch, tmp_path):
    """Same plumbing smoke for the MXU (GEMM) tile autotuner, MFU report."""
    from pathlib import Path

    monkeypatch.syspath_prepend(str(Path(__file__).parents[1] / "scripts"))
    import autotune_pallas_gemm

    monkeypatch.setattr(autotune_pallas_gemm, "BMS", (128,))
    monkeypatch.setattr(autotune_pallas_gemm, "BNS", (128,))
    monkeypatch.setattr(autotune_pallas_gemm, "BKS", (128,))
    report = tmp_path / "AUTOTUNE_GEMM.md"
    rc = autotune_pallas_gemm.main([
        "--platform", "cpu", "--allow-interpret", "--size", "256",
        "--n-reps", "1", "--samples", "1", "--report", str(report),
    ])
    assert rc == 0
    text = report.read_text()
    assert "pallas 128x128x128" in text
    assert "MFU" in text
    assert "Best tile" in text


def test_profiling_trace(devices, tmp_path):
    with trace(tmp_path / "prof") as d:
        with annotate("matvec-region"):
            jnp.dot(jnp.ones((64, 64)), jnp.ones(64)).block_until_ready()
    files = list((tmp_path / "prof").rglob("*"))
    assert files, "trace produced no files"


def test_profiling_disabled(tmp_path):
    with trace(tmp_path / "prof2", enabled=False) as d:
        assert d is None
    assert not (tmp_path / "prof2").exists()


def test_checkpoint_roundtrip_sharded(devices, rng, tmp_path):
    """Save a sharded TrainState, restore into the same shardings, resume."""
    mesh = make_mesh(8)
    opt = optax.sgd(1e-2)
    a = rng.standard_normal((16, 16)).astype(np.float32)
    b = rng.standard_normal(16).astype(np.float32)
    sh = trainer.shardings(mesh)
    a_dev = jax.device_put(jnp.asarray(a), sh["a"])
    b_dev = jax.device_put(jnp.asarray(b), sh["b"])
    step = trainer.build_train_step(mesh, opt)
    state = trainer.init_state(mesh, 16, opt)
    for _ in range(3):
        state, _ = step(state, a_dev, b_dev)

    path = checkpoint.save_state(state, tmp_path / "ckpt" / "step_3")
    template = trainer.init_state(mesh, 16, opt)
    restored = checkpoint.restore_state(path, template)

    assert int(restored.step) == 3
    assert restored.x.sharding == state.x.sharding
    np.testing.assert_allclose(np.asarray(restored.x), np.asarray(state.x))

    # Resumed trajectory == uninterrupted trajectory.
    cont_a, _ = step(state, a_dev, b_dev)
    cont_b, _ = step(restored, a_dev, b_dev)
    np.testing.assert_allclose(np.asarray(cont_a.x), np.asarray(cont_b.x))


def test_latest_step_dir(tmp_path):
    assert checkpoint.latest_step_dir(tmp_path / "none") is None
    for s in (1, 5, 10):
        (tmp_path / f"step_{s}").mkdir()
    (tmp_path / "step_bogus").mkdir()
    assert checkpoint.latest_step_dir(tmp_path).name == "step_10"


def test_generate_data_cli(tmp_path, capsys):
    import sys
    sys.path.insert(0, "/root/repo/scripts")
    import generate_data

    rc = generate_data.main(["24", "16", "--data-root", str(tmp_path)])
    assert rc == 0
    assert (tmp_path / "matrix_24_16.txt").exists()
    assert (tmp_path / "vector_16.txt").exists()
    from matvec_mpi_multiplier_tpu.utils import io
    a = io.load_matrix(24, 16, tmp_path)
    x = io.load_vector(16, tmp_path)
    assert a.shape == (24, 16) and x.shape == (16,)


def test_generate_data_cli_requires_args():
    import generate_data
    with pytest.raises(SystemExit):
        generate_data.main([])


def test_refine_study_cli_smoke(monkeypatch, tmp_path):
    """End-to-end plumbing of the refinement study on the CPU backend:
    tiny ladder, report generation, measured-gain line."""
    from pathlib import Path

    monkeypatch.syspath_prepend(str(Path(__file__).parents[1] / "scripts"))
    import refine_study

    monkeypatch.setattr(refine_study, "CONDS", (1e2,))
    report = tmp_path / "REFINEMENT.md"
    rc = refine_study.main([
        "--platform", "cpu", "--size", "64", "--max-iters", "500",
        "--report", str(report),
    ])
    assert rc == 0
    text = report.read_text()
    assert "| 1e+02 |" in text
    assert "refined" in text


def test_refine_study_marks_capped_cg_control(monkeypatch, tmp_path):
    """When a control solver reports non-convergence by exhausting the
    iteration budget, its error and iteration cells are starred — for
    plain CG AND the PCG control — and the report says the control is
    truncated, not converged: the gain claim must never silently compare
    against a truncated run. The mark keys on CGResult.converged (the
    true-residual check), not on the iteration count alone (n_iters ==
    max_iters can coincide with convergence on the final step); a
    non-converged control that stopped BELOW the budget gets the
    distinct floor mark instead (more iterations would not have
    helped)."""
    from pathlib import Path

    monkeypatch.syspath_prepend(str(Path(__file__).parents[1] / "scripts"))
    import refine_study

    monkeypatch.setattr(refine_study, "CONDS", (1e4,))
    report = tmp_path / "REFINEMENT.md"
    rc = refine_study.main([
        "--platform", "cpu", "--size", "64", "--max-iters", "5",
        "--report", str(report),
    ])
    assert rc == 0
    text = report.read_text()
    assert "| 5\\* |" in text
    assert "truncated run, not a converged one" in text
    # Both control columns carry the star: CG err, PCG err, then an
    # unstarred refined err — three starred cells per capped row in
    # total (cg err, pcg err, cg iters).
    row = next(line for line in text.splitlines() if "| 5\\* |" in line)
    assert row.count("\\*") == 3


def test_refine_study_floor_mark_distinct_from_budget_mark(
    monkeypatch, tmp_path
):
    """A control that stops short of tol with budget to spare (fp32 CG's
    attainable floor — tol=1e-7 is below what fp32 arithmetic can reach)
    is marked with the floor dagger, not the truncation star, and the
    floor footnote explains that more iterations would not have
    helped."""
    from pathlib import Path

    monkeypatch.syspath_prepend(str(Path(__file__).parents[1] / "scripts"))
    import refine_study

    monkeypatch.setattr(refine_study, "CONDS", (1e2,))
    report = tmp_path / "REFINEMENT.md"
    rc = refine_study.main([
        "--platform", "cpu", "--size", "256", "--max-iters", "20000",
        "--report", str(report),
    ])
    assert rc == 0
    text = report.read_text()
    # At cond 1e2 with an effectively unlimited budget, CG exits on its
    # recurrence stagnation well under the cap but the true residual
    # stays above tol*||b||: the dagger sub-case.
    assert "†" in text
    assert "stopped short of `tol` with budget to spare" in text
    assert "truncated run" not in text


def test_attention_study_cli_smoke(monkeypatch, tmp_path):
    """End-to-end plumbing of the attention study on the CPU backend:
    tiny ladder, correctness asserts, report generation."""
    from pathlib import Path

    monkeypatch.syspath_prepend(str(Path(__file__).parents[1] / "scripts"))
    import attention_study

    report = tmp_path / "ATTENTION.md"
    rc = attention_study.main([
        "--platform", "cpu", "--seqs", "64", "--heads", "8", "--d-head", "8",
        "--n-reps", "2", "--report", str(report),
    ])
    assert rc == 0
    text = report.read_text()
    assert "| 64 |" in text
    assert "ulysses" in text


def _watcher_env(tmp_path, probe_failures: int, capture_rcs: list[int]) -> dict:
    """PATH-shadow ``python`` so scripts/watch_and_capture.sh runs against a
    scripted backend: the probe (a ``python -c`` call) fails
    ``probe_failures`` times then succeeds; each capture invocation
    (``python scripts/tpu_measure_all.py``) pops the next rc from
    ``capture_rcs`` (empty list -> always 1)."""
    import os
    import stat

    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    (tmp_path / "probe_failures").write_text(str(probe_failures))
    (tmp_path / "capture_rcs").write_text(
        "\n".join(str(rc) for rc in capture_rcs)
    )
    stub = bin_dir / "python"
    stub.write_text(f"""#!/bin/bash
state={tmp_path}
case "$*" in
  *tpu_measure_all.py*)
    echo "$*" >> "$state/capture_argvs"
    rcs=$(cat "$state/capture_rcs")
    rc=${{rcs%%$'\\n'*}}; [ -z "$rc" ] && rc=1
    rest=${{rcs#*$'\\n'}}; [ "$rest" = "$rcs" ] && rest=""
    printf '%s' "$rest" > "$state/capture_rcs"
    exit "$rc" ;;
  *)
    n=$(cat "$state/probe_failures")
    if [ "$n" -gt 0 ]; then echo $((n - 1)) > "$state/probe_failures"; exit 1; fi
    exit 0 ;;
esac
""")
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    env = dict(os.environ)
    env["PATH"] = f"{bin_dir}:{env['PATH']}"
    env["WATCH_INTERVAL_S"] = "0"
    env["WATCH_PROBE_TIMEOUT_S"] = "10"
    return env


def test_watcher_failed_probes_never_consume_the_attempt_budget(tmp_path):
    """8+ hour wedges are the observed norm: a watcher whose budget could
    expire on failed probes would sit idle through the one healthy window
    that matters. Five failed probes, then a healthy one, then a capture
    that succeeds — with a budget of ONE capture attempt."""
    import subprocess
    from pathlib import Path

    repo = Path(__file__).parents[1]
    env = _watcher_env(tmp_path, probe_failures=5, capture_rcs=[0])
    env["WATCH_MAX_ATTEMPTS"] = "1"
    r = subprocess.run(
        ["bash", str(repo / "scripts" / "watch_and_capture.sh")],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert "capture succeeded on attempt 1" in r.stderr
    assert r.stderr.count("probe failed/hung") == 5


def test_watcher_gives_up_after_the_configured_capture_attempts(tmp_path):
    import subprocess
    from pathlib import Path

    repo = Path(__file__).parents[1]
    env = _watcher_env(tmp_path, probe_failures=0, capture_rcs=[1, 1])
    env["WATCH_MAX_ATTEMPTS"] = "2"
    r = subprocess.run(
        ["bash", str(repo / "scripts" / "watch_and_capture.sh")],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 1, r.stderr
    # The give-up line reports attempts actually made (not a raw 0 budget).
    assert "giving up after 2 capture attempts" in r.stderr


def test_watcher_default_budget_is_unlimited(tmp_path):
    """The default (WATCH_MAX_ATTEMPTS unset -> 0) must keep retrying past
    any finite budget: 7 failed captures, then one success."""
    import subprocess
    from pathlib import Path

    repo = Path(__file__).parents[1]
    env = _watcher_env(tmp_path, probe_failures=0, capture_rcs=[1] * 7 + [0])
    env.pop("WATCH_MAX_ATTEMPTS", None)
    r = subprocess.run(
        ["bash", str(repo / "scripts" / "watch_and_capture.sh")],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert "capture succeeded on attempt 8" in r.stderr
    assert "attempt 8/inf" in r.stderr


def test_watcher_passes_args_through_on_every_attempt(tmp_path):
    """The watcher passes its args (incl. --wipe-stale-csvs) through
    unchanged on every attempt: once-per-round wipe semantics live in the
    capture's sentinel (test below), NOT in fragile argv filtering — a
    prefix-abbreviated flag (argparse accepts those) would dodge any
    string filter."""
    import subprocess
    from pathlib import Path

    repo = Path(__file__).parents[1]
    env = _watcher_env(tmp_path, probe_failures=0, capture_rcs=[1, 0])
    r = subprocess.run(
        ["bash", str(repo / "scripts" / "watch_and_capture.sh"),
         "--wipe-stale-csvs", "--data-root", "data"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    argvs = (tmp_path / "capture_argvs").read_text().splitlines()
    assert len(argvs) == 2
    for argv in argvs:
        assert "--wipe-stale-csvs" in argv
        assert "--data-root data" in argv


def test_wipe_stale_csvs_is_once_per_round(monkeypatch, tmp_path):
    """--wipe-stale-csvs retires rows from OLDER protocols exactly once
    per round: the first wipe moves CSVs aside and writes the
    .stale_wiped sentinel; under the sentinel a retrying capture leaves
    the partial dataset its own earlier attempt flushed (sweeps resume
    via --skip-measured). A landed round re-arms the wipe (the landing
    test covers sentinel removal)."""
    from pathlib import Path

    monkeypatch.syspath_prepend(str(Path(__file__).parents[1] / "scripts"))
    import tpu_measure_all

    out = tmp_path / "out"
    out.mkdir(parents=True)
    (out / "rowwise.csv").write_text("old protocol rows\n")
    tpu_measure_all._wipe_stale_csvs(out)
    assert not (out / "rowwise.csv").exists()
    assert (out / "rowwise.csv.stale").exists()
    assert (out / ".stale_wiped").exists()

    # Attempt 2 of the same round: the partial dataset survives.
    (out / "rowwise.csv").write_text("this round's partial rows\n")
    tpu_measure_all._wipe_stale_csvs(out)
    assert (out / "rowwise.csv").read_text() == (
        "this round's partial rows\n"
    )


def test_land_capture_rehearsal(monkeypatch, tmp_path):
    """Full rehearsal of the capture-landing script against a synthetic
    repo tree: inventory, north-star update, README table splice — so
    capture day exercises a proven path, not a first run."""
    from pathlib import Path

    repo = Path(__file__).parents[1]
    monkeypatch.syspath_prepend(str(repo / "scripts"))
    # Synthetic repo tree: tiny real dataset via the sweep CLI would be
    # slow here; hand-write loop rows in the extended schema instead.
    out = tmp_path / "data" / "out"
    out.mkdir(parents=True)
    header = ("n_rows, n_cols, n_devices, time, strategy, dtype, mode, "
              "measure, gflops, gbps, n_rhs\n")
    strategies = ("rowwise", "colwise", "colwise_ring",
                  "colwise_ring_overlap", "colwise_a2a", "blockwise")
    ext_rows = []
    for s in strategies:
        (out / f"{s}.csv").write_text(
            "n_rows, n_cols, n_processes, time\n600, 600, 1, 0.001\n"
        )
        ext_rows.append(
            f"600, 600, 1, 0.001, {s}, float32, amortized, loop, "
            "0.72, 2.88, 1\n"
        )
        # One asymmetric-regime row per strategy: the splice must render
        # BOTH regime tables (the reference's asymmetric_*.csv face).
        ext_rows.append(
            f"120, 60000, 1, 0.002, {s}, float32, amortized, loop, "
            "7.2, 28.8, 1\n"
        )
    (out / "results_extended.csv").write_text(header + "".join(ext_rows))
    (out / "vmem_roof.json").write_text('{"ceiling_per_chip_gbps": 1000}')
    (out / "superseded").mkdir()
    (out / "superseded" / "old.csv").write_text("stale\n")
    (tmp_path / "figures" / "tpu").mkdir(parents=True)
    (tmp_path / "BASELINE_65536_bf16.json").write_text(
        '{"metric": "blockwise_bandwidth", "value": 777.5, "unit": "GB/s"}'
    )
    (tmp_path / "BASELINE.json").write_text(
        '{"published": {"blockwise_65536_bf16_hbm_sweep": '
        '{"status": "blocked_tunnel", "best_measured_gbps": null}}}'
    )
    (tmp_path / "README.md").write_text(
        "# x\n\n<!-- TPU_RESULTS_TABLE_START -->\npending\n"
        "<!-- TPU_RESULTS_TABLE_END -->\n"
    )
    (tmp_path / "README_RU.md").write_text(
        "# y\n\n<!-- TPU_RESULTS_TABLE_START -->\npending-ru\n"
        "<!-- TPU_RESULTS_TABLE_END -->\n"
    )

    # Gates would run against the REAL repo's committed data (still
    # pre-capture), so rehearse via the module with _gates stubbed and
    # REPO pointed at the synthetic tree.
    import importlib

    import land_capture

    importlib.reload(land_capture)
    monkeypatch.setattr(land_capture, "REPO", tmp_path)
    monkeypatch.setattr(
        land_capture, "_gates", lambda: (True, "stubbed green")
    )
    # A capture ran this round: its once-per-round wipe sentinel is
    # present and landing must clear it (re-arming the next round's wipe).
    (out / ".stale_wiped").write_text("wiped\n")
    rc = land_capture.main(["--apply", "--retire-superseded"])
    assert rc == 0
    assert not (out / ".stale_wiped").exists()

    import json

    baseline = json.loads((tmp_path / "BASELINE.json").read_text())
    entry = baseline["published"]["blockwise_65536_bf16_hbm_sweep"]
    assert entry["status"] == "published"
    assert entry["best_measured_gbps"] == 777.5
    readme = (tmp_path / "README.md").read_text()
    assert "| 600² |" in readme and "pending" not in readme
    assert "| 120×60000 |" in readme  # the asymmetric table landed too
    readme_ru = (tmp_path / "README_RU.md").read_text()
    assert "| 600² |" in readme_ru and "pending-ru" not in readme_ru
    assert "Квадратный режим" in readme_ru  # RU caption, same tables
    assert not (out / "superseded").exists()

    # Idempotence: a second --apply re-splices cleanly between markers.
    rc = land_capture.main(["--apply"])
    assert rc == 0
    readme2 = (tmp_path / "README.md").read_text()
    assert readme2.count("TPU_RESULTS_TABLE_START") == 1


def test_watcher_stops_on_completed_capture_with_failed_stages(tmp_path):
    """Capture rc=4 means every stage RAN but some hard-failed —
    deterministic, so an unlimited-retry watcher must stop instead of
    re-running the whole multi-hour capture in a loop through the healthy
    window. Retryable aborts (rc=1) before it still retry."""
    import subprocess
    from pathlib import Path

    repo = Path(__file__).parents[1]
    env = _watcher_env(tmp_path, probe_failures=0, capture_rcs=[1, 4, 0])
    env.pop("WATCH_MAX_ATTEMPTS", None)
    r = subprocess.run(
        ["bash", str(repo / "scripts" / "watch_and_capture.sh")],
        env=env, capture_output=True, text=True, timeout=120,
    )
    # rc=1 retried; rc=4 stopped the loop — the queued rc=0 never ran.
    assert r.returncode == 2, r.stderr
    assert "aborted (rc=1, wedge/probe)" in r.stderr
    assert "attempt 2 ended rc=4 (deterministic" in r.stderr
    assert "attempt 3" not in r.stderr


def test_attention_study_isolates_variant_failures(monkeypatch, tmp_path):
    """A variant that cannot run (here: Ulysses with h=2 on an 8-device
    mesh) must cost only its own columns — the report still lands with the
    healthy variants' numbers, and the stage exits nonzero so the capture
    records the finding. The capture gets one shot per healthy window; a
    Mosaic lowering quirk in one tier must not void the others' evidence."""
    from pathlib import Path

    monkeypatch.syspath_prepend(str(Path(__file__).parents[1] / "scripts"))
    import attention_study

    report = tmp_path / "ATTENTION.md"
    rc = attention_study.main([
        "--platform", "cpu", "--seqs", "64", "--heads", "2", "--d-head", "8",
        "--n-reps", "2", "--report", str(report),
    ])
    assert rc == 1
    text = report.read_text()
    # The broken variants are named in their TABLE cells, not silently
    # absent — and the healthy variants' row still landed. Count cells on
    # the data row only: the legend also mentions the FAILED marker.
    row = next(l for l in text.splitlines() if l.startswith("| 64 |"))
    assert row.count("FAILED") == 2
    assert row.count("ms") == 0 and "|" in row


def test_autotune_attention_cli_smoke(monkeypatch, tmp_path):
    """Same plumbing smoke for the flash-attention tile autotuner."""
    from pathlib import Path

    monkeypatch.syspath_prepend(str(Path(__file__).parents[1] / "scripts"))
    import autotune_pallas_attention

    monkeypatch.setattr(autotune_pallas_attention, "BQS", (128,))
    monkeypatch.setattr(autotune_pallas_attention, "BKS", (128,))
    report = tmp_path / "AUTOTUNE_ATTENTION.md"
    rc = autotune_pallas_attention.main([
        "--platform", "cpu", "--allow-interpret", "--size", "128",
        "--heads", "2", "--n-reps", "1", "--samples", "1",
        "--report", str(report),
    ])
    assert rc == 0
    text = report.read_text()
    assert "flash 128x128" in text
    assert "xla tier" in text
    assert "Best tile" in text
    # A non-lane-multiple head size has no kernel to tune: usage error.
    assert autotune_pallas_attention.main(
        ["--platform", "cpu", "--allow-interpret", "--d-head", "64"]
    ) == 2


def test_land_capture_aborts_before_any_write_on_unrenderable_dataset(
    monkeypatch, tmp_path
):
    """The nothing-half-landed invariant: a dataset whose rows miss the
    renderer's filters (here: sync-measure rows only, no loop rows) must
    abort BEFORE BASELINE.json or README.md are touched — a north star
    published without its README table would be a half-landed capture."""
    from pathlib import Path

    repo = Path(__file__).parents[1]
    monkeypatch.syspath_prepend(str(repo / "scripts"))
    out = tmp_path / "data" / "out"
    out.mkdir(parents=True)
    header = ("n_rows, n_cols, n_devices, time, strategy, dtype, mode, "
              "measure, gflops, gbps, n_rhs\n")
    (out / "results_extended.csv").write_text(
        header
        + "600, 600, 1, 0.001, rowwise, float32, amortized, sync, "
        "0.72, 2.88, 1\n"
    )
    (out / "vmem_roof.json").write_text('{"ceiling_per_chip_gbps": 1000}')
    baseline_before = (
        '{"published": {"blockwise_65536_bf16_hbm_sweep": '
        '{"status": "blocked_tunnel", "best_measured_gbps": null}}}'
    )
    (tmp_path / "BASELINE.json").write_text(baseline_before)
    (tmp_path / "BASELINE_65536_bf16.json").write_text(
        '{"metric": "m", "value": 777.5, "unit": "GB/s"}'
    )
    readme_before = (
        "# x\n\n<!-- TPU_RESULTS_TABLE_START -->\npending\n"
        "<!-- TPU_RESULTS_TABLE_END -->\n"
    )
    (tmp_path / "README.md").write_text(readme_before)
    (tmp_path / "README_RU.md").write_text(readme_before)

    import importlib

    import land_capture

    importlib.reload(land_capture)
    monkeypatch.setattr(land_capture, "REPO", tmp_path)
    monkeypatch.setattr(land_capture, "_gates", lambda: (True, "stubbed"))
    rc = land_capture.main(["--apply"])
    assert rc == 1
    # Nothing was written: both files byte-identical to before.
    assert (tmp_path / "BASELINE.json").read_text() == baseline_before
    assert (tmp_path / "README.md").read_text() == readme_before

"""`check_rep` (JAX 0.4.x) vs `check_vma` (current JAX) parity audit.

ROADMAP item: the compat shim (``utils/compat.py``) maps ``check_vma`` onto
``check_rep`` on old installs — do the two enforce the same contract?

Audit findings (probed on JAX 0.4.37, the container's install, and pinned
here so a regression or a JAX upgrade surfaces as a test diff):

1. **Acceptance parity holds.** Everything check_rep can analyze, it
   enforces at least as strictly as check_vma: an under-replicated body
   returned through ``out_specs=P()`` (missing psum, partial-axis psum on a
   2-D mesh, a bare ``axis_index``, a ppermute chain that is replicated in
   value but not provably) is REJECTED on both generations. No case was
   found where check_rep silently accepts a body the vma checker rejects.

2. **Coverage is the weaker contract.** check_rep has NO replication rule
   for several primitives — ``while`` (lax.while_loop), ``pallas_call``
   among them — and raises ``NotImplementedError`` even for perfectly VALID
   bodies containing them. The only recourse is ``check_rep=False``, which
   waives the psum/out_specs contract for the WHOLE body: on 0.4.x, any
   shard_map whose body contains a while-loop or a pallas kernel runs with
   replication checking silently absent, where the vma generation keeps
   verifying everything else in the body. This is the one contract the
   0.4.x path enforces more weakly — by coverage, not by acceptance.

3. **The repo's mitigation is scoping.** Because turning the check off is
   all-or-nothing per shard_map, ``models/base.py`` confines relaxation to
   the smallest program unit: the ring-gather stage gets its own shard_map
   with the check off while the compute body's psum contract stays
   enforced, and pallas-backed kernels/bodies relax only their own build
   (``relax_vma_check``). These scoping seams are pinned here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from matvec_mpi_multiplier_tpu import get_strategy, make_mesh
from matvec_mpi_multiplier_tpu.parallel.mesh import make_1d_mesh
from matvec_mpi_multiplier_tpu.utils.compat import HAS_VMA, shard_map


def _run(body, mesh, in_specs, out_specs, x, check=True):
    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=check,
    ))(x)


# ------------------------------------------------ acceptance parity (1)


def test_missing_psum_rejected(devices):
    """A device-varying value through out_specs=P() must be rejected under
    the check on BOTH generations."""
    mesh = make_1d_mesh(8, axis_name="d")
    x = jnp.arange(8.0)
    with pytest.raises(Exception, match="replicat|vma"):
        _run(lambda a: a.sum(keepdims=True), mesh, (P("d"),), P(), x)


def test_partial_axis_psum_rejected(devices):
    """psum over one axis of a 2-D mesh does not replicate over the other:
    out_specs=P() must be rejected on both generations."""
    mesh = make_mesh(8)  # ('rows', 'cols')
    x = jnp.arange(8.0)
    with pytest.raises(Exception, match="replicat|vma"):
        _run(
            lambda a: jax.lax.psum(a.sum(keepdims=True), "cols"),
            mesh, (P(("rows", "cols")),), P(), x,
        )


def test_axis_index_rejected(devices):
    mesh = make_1d_mesh(8, axis_name="d")
    x = jnp.arange(8.0)
    with pytest.raises(Exception, match="replicat|vma"):
        _run(
            lambda a: jnp.zeros((1,)) + jax.lax.axis_index("d"),
            mesh, (P("d"),), P(), x,
        )


def test_full_psum_accepted(devices):
    """The valid formulation passes the check on both generations."""
    mesh = make_mesh(8)
    x = jnp.arange(8.0)
    out = _run(
        lambda a: jax.lax.psum(a.sum(keepdims=True), ("rows", "cols")),
        mesh, (P(("rows", "cols")),), P(), x,
    )
    np.testing.assert_allclose(np.asarray(out), [28.0])


def test_ppermute_gather_unprovable_on_both(devices):
    """A ring all-gather's result is replicated in VALUE but neither
    checker can prove it (ppermute outputs stay axis-varying) — the reason
    ring_all_gather callers must scope the check off. Pinned as rejected on
    both generations so a future JAX that learns to prove it shows up."""
    from matvec_mpi_multiplier_tpu.parallel.ring import ring_all_gather

    mesh = make_1d_mesh(8, axis_name="d")
    x = jnp.arange(8.0)
    with pytest.raises(Exception, match="replicat|vma"):
        _run(lambda a: ring_all_gather(a, "d"), mesh, (P("d"),), P(), x)
    # With the check scoped off, the gather is correct.
    out = _run(
        lambda a: ring_all_gather(a, "d"), mesh, (P("d"),), P(), x,
        check=False,
    )
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0))


# ------------------------------------------------- coverage audit (2)


@pytest.mark.skipif(
    HAS_VMA, reason="vma-generation JAX tracks these primitives; the "
    "no-rule failure mode is specific to the 0.4.x check_rep path",
)
def test_check_rep_has_no_rule_for_while(devices):
    """THE documented weaker contract: a VALID body (value made replicated
    by a full psum, then carried through a while_loop) cannot be verified
    at all — check_rep raises NotImplementedError, forcing the caller to
    disable checking wholesale."""
    mesh = make_1d_mesh(8, axis_name="d")
    x = jnp.arange(8.0)

    def body(a):
        s = jax.lax.psum(a.sum(), "d")
        val = jax.lax.while_loop(lambda v: v < s, lambda v: v + 100.0, 0.0)
        return jnp.zeros((1,)) + val

    with pytest.raises(NotImplementedError, match="[Nn]o replication rule"):
        _run(body, mesh, (P("d"),), P(), x)
    # The forced waiver: with the check off the same body runs — and so
    # would any OTHER contract violation in the body (the coverage gap).
    out = _run(body, mesh, (P("d"),), P(), x, check=False)
    assert np.asarray(out)[0] >= 28.0


@pytest.mark.skipif(
    HAS_VMA, reason="vma-generation JAX tracks pallas_call; the no-rule "
    "failure mode is specific to the 0.4.x check_rep path",
)
def test_check_rep_has_no_rule_for_pallas_call(devices):
    """Same coverage gap for pallas_call: the reason models/base.py keys
    check relaxation off `relax_vma_check` rather than trusting the
    checker to handle pallas-backed bodies."""
    from jax.experimental import pallas as pl

    mesh = make_1d_mesh(8, axis_name="d")
    x = jnp.arange(8.0)

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 1.0

    def body(a):
        s = jax.lax.psum(a, "d")  # replicated — a valid P() output
        return pl.pallas_call(
            kern, out_shape=jax.ShapeDtypeStruct(s.shape, s.dtype),
            interpret=True,
        )(s)

    with pytest.raises(NotImplementedError, match="[Nn]o replication rule"):
        _run(body, mesh, (P("d"),), P(), x)


# ------------------------------------------------ scoping seams (3)


def test_ring_gather_scopes_check_to_gather_stage(devices, rng):
    """build(gather_output='ring') relaxes the check ONLY for the gather
    shard_map: the compute body keeps its psum/out_specs contract. Pinned
    by checking both stages exist as separate shard_maps with the expected
    flags is an implementation detail; the observable contract is that the
    build works on both generations AND a compute-body violation still
    fails."""
    a = rng.standard_normal((16, 16))
    x = rng.standard_normal(16)
    mesh = make_mesh(8)
    y = get_strategy("rowwise").build(mesh, gather_output="ring")(
        jnp.asarray(a), jnp.asarray(x)
    )
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-10)


def test_pallas_kernel_relaxation_is_keyed_not_blanket(devices, rng):
    """A pallas-backed kernel builds with the check relaxed (it could not
    build otherwise on 0.4.x — the no-rule gap above); the XLA kernel path
    keeps the checker on. Both must produce the oracle product."""
    a = rng.standard_normal((16, 16))
    x = rng.standard_normal(16)
    mesh = make_mesh(8)
    for kernel in ("xla", "pallas"):
        y = get_strategy("colwise").build(mesh, kernel=kernel)(
            jnp.asarray(a), jnp.asarray(x)
        )
        np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-6), kernel


def test_overlap_gather_scopes_check_off(devices, rng):
    """The staged overlap gather (combine='overlap' on sharded-output
    strategies) rides ppermute chains through out_specs=P() — same
    unprovable-replication situation as ring_all_gather, same scoped
    check_vma=False, usable on both generations."""
    a = rng.standard_normal((16, 16))
    x = rng.standard_normal(16)
    mesh = make_mesh(8)
    y = get_strategy("blockwise").build(mesh, combine="overlap", stages=2)(
        jnp.asarray(a), jnp.asarray(x)
    )
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-10)

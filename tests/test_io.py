"""Data/IO layer tests: filename convention, round-trip, error paths.

Contract under test is ``src/matr_utils.c`` (see utils/io.py docstring).
"""

import numpy as np
import pytest

from matvec_mpi_multiplier_tpu.utils import io
from matvec_mpi_multiplier_tpu.utils.errors import DataFileError

from conftest import FIXTURE_MATRIX, FIXTURE_VECTOR


def test_filename_convention(tmp_path):
    assert io.matrix_path(600, 1200, tmp_path).name == "matrix_600_1200.txt"
    assert io.vector_path(600, tmp_path).name == "vector_600.txt"


def test_roundtrip_matrix(tmp_path, rng):
    a = np.round(rng.uniform(0, 10, size=(6, 4)), 4)
    io.save_matrix(a, tmp_path)
    loaded = io.load_matrix(6, 4, tmp_path)
    np.testing.assert_array_equal(loaded, a)


def test_roundtrip_vector(tmp_path, rng):
    v = np.round(rng.uniform(0, 10, size=(16,)), 4)
    io.save_vector(v, tmp_path)
    np.testing.assert_array_equal(io.load_vector(16, tmp_path), v)


def test_reference_fixture_format(tmp_path):
    """Our writer emits files the reference loader contract accepts, and our
    loader reads the exact committed 4×8 fixture layout."""
    io.save_matrix(FIXTURE_MATRIX, tmp_path)
    io.save_vector(FIXTURE_VECTOR, tmp_path)
    a = io.load_matrix(4, 8, tmp_path)
    x = io.load_vector(8, tmp_path)
    np.testing.assert_allclose(a @ x, [222.2, 196.55, 191.57, 232.9], rtol=1e-12)


def test_committed_fixture_files():
    """The fixture committed in this repo's data/ (reference parity, C11)
    must load through the convention loaders and give the known product."""
    root = "/root/repo/data"
    a = io.load_matrix(4, 8, root)
    x = io.load_vector(8, root)
    np.testing.assert_array_equal(a, FIXTURE_MATRIX)
    np.testing.assert_array_equal(x, FIXTURE_VECTOR)
    np.testing.assert_allclose(a @ x, [222.2, 196.55, 191.57, 232.9], rtol=1e-12)


def test_debug_printers():
    """print_matr/print_vec analogs (src/matr_utils.c:21-39)."""
    assert io.format_matrix(np.array([[1.234, 5.0]])) == "1.23 5.00"
    assert io.format_matrix(np.array([1.0, 2.0])) == "1.00 2.00"  # 1-D promotes
    assert io.format_vector(np.array([1.5, 2.25]), precision=1) == "1.5\n2.2"
    import pytest as _pytest
    with _pytest.raises(DataFileError, match="1-D or 2-D"):
        io.format_matrix(np.zeros((2, 2, 2)))


def test_missing_file_raises(tmp_path):
    with pytest.raises(DataFileError, match="Unable to locate"):
        io.load_matrix(3, 3, tmp_path)
    with pytest.raises(DataFileError, match="Unable to locate"):
        io.load_vector(3, tmp_path)


def test_size_mismatch_raises(tmp_path):
    io.save_matrix(np.ones((2, 3)), tmp_path)
    (io.matrix_path(5, 5, tmp_path)).write_text(
        io.matrix_path(2, 3, tmp_path).read_text()
    )
    with pytest.raises(DataFileError, match="expected"):
        io.load_matrix(5, 5, tmp_path)


def test_ensure_data_generates(tmp_path):
    a, x = io.ensure_data(8, 16, tmp_path)
    assert a.shape == (8, 16) and x.shape == (16,)
    assert io.matrix_path(8, 16, tmp_path).exists()
    assert io.vector_path(16, tmp_path).exists()
    # idempotent: second call loads the same values
    a2, x2 = io.ensure_data(8, 16, tmp_path)
    np.testing.assert_array_equal(a, a2)
    np.testing.assert_array_equal(x, x2)


def test_generator_determinism():
    np.testing.assert_array_equal(
        io.generate_matrix(4, 4, seed=7), io.generate_matrix(4, 4, seed=7)
    )
    assert not np.array_equal(
        io.generate_matrix(4, 4, seed=7), io.generate_matrix(4, 4, seed=8)
    )


# ---------- native text loader (native/textio.cc) ----------

def _native_io_available():
    from matvec_mpi_multiplier_tpu.utils.io import _native_lib

    return _native_lib() is not None


@pytest.mark.skipif(
    not _native_io_available(), reason="native lib not built (make -C native)"
)
def test_native_loader_matches_numpy(tmp_path, monkeypatch):
    from matvec_mpi_multiplier_tpu.utils import io

    a = io.generate_matrix(37, 53, seed=9)
    io.save_matrix(a, tmp_path)
    native = io.load_matrix(37, 53, tmp_path)
    monkeypatch.setenv("MATVEC_NATIVE_IO", "0")
    via_numpy = io.load_matrix(37, 53, tmp_path)
    np.testing.assert_array_equal(native, via_numpy)


@pytest.mark.skipif(
    not _native_io_available(), reason="native lib not built (make -C native)"
)
def test_native_loader_count_mismatch(tmp_path):
    from matvec_mpi_multiplier_tpu.utils import io

    (tmp_path / "vector_9.txt").write_text("1\n2\n3\n4\n5\n6\n7\n8\n")
    with pytest.raises(DataFileError, match="expected"):
        io.load_vector(9, tmp_path)  # too few values in the file
    # Too many values must also be rejected (the has-more probe).
    (tmp_path / "vector_4.txt").write_text("1\n2\n3\n4\n5\n")
    with pytest.raises(DataFileError, match="expected"):
        io.load_vector(4, tmp_path)


def test_numpy_fallback_env(tmp_path, monkeypatch):
    from matvec_mpi_multiplier_tpu.utils import io

    monkeypatch.setenv("MATVEC_NATIVE_IO", "0")
    io.save_vector(np.arange(5.0), tmp_path)
    np.testing.assert_array_equal(io.load_vector(5, tmp_path), np.arange(5.0))


@pytest.mark.skipif(
    not _native_io_available(), reason="native lib not built (make -C native)"
)
def test_native_loader_strtod_fallback_tokens(tmp_path, monkeypatch):
    # e-notation / >15-digit tokens route through the strtod fallback and
    # must stay bitwise identical to the numpy parser.
    (tmp_path / "vector_6.txt").write_text(
        "1.5e3 -2.25E-2 0.123456789012345678 42 -0 7.0001\n"
    )
    from matvec_mpi_multiplier_tpu.utils import io

    native = io.load_vector(6, tmp_path)
    monkeypatch.setenv("MATVEC_NATIVE_IO", "0")
    via_numpy = io.load_vector(6, tmp_path)
    np.testing.assert_array_equal(native, via_numpy)


@pytest.mark.skipif(
    not _native_io_available(), reason="native lib not built (make -C native)"
)
def test_native_loader_rejects_malformed(tmp_path):
    # Both parser paths must reject the same files: trailing garbage and
    # fused tokens fall back to numpy, which raises.
    (tmp_path / "vector_4.txt").write_text("1 2 3 abc\n")
    with pytest.raises(Exception):
        io.load_vector(4, tmp_path)
    (tmp_path / "vector_2.txt").write_text("1.5-2.5\n")
    with pytest.raises(Exception):
        io.load_vector(2, tmp_path)


@pytest.mark.skipif(
    not _native_io_available(), reason="native lib not built (make -C native)"
)
def test_native_loader_rejects_ragged_lines(tmp_path):
    # np.loadtxt rejects ragged rows even when the total element count
    # matches ("Wrong number of columns at line N"); the native path must
    # agree. 3 + 5 tokens = 8 = 2*4, so only line structure distinguishes it.
    (tmp_path / "matrix_2_4.txt").write_text("1 2 3\n4 5 6 7 8\n")
    with pytest.raises(Exception):
        io.load_matrix(2, 4, tmp_path)
    # Blank lines are not ragged — numpy skips them; so must the native path.
    (tmp_path / "matrix_2_2.txt").write_text("1 2\n\n3 4\n")
    np.testing.assert_array_equal(
        io.load_matrix(2, 2, tmp_path), np.array([[1.0, 2.0], [3.0, 4.0]])
    )


@pytest.mark.skipif(
    not _native_io_available(), reason="native lib not built (make -C native)"
)
def test_native_loader_rejects_hex_floats(tmp_path):
    # strtod accepts C99 hex-floats; numpy does not — the native path must
    # agree with numpy and reject the file.
    (tmp_path / "vector_2.txt").write_text("0x1p3 2.0\n")
    with pytest.raises(Exception):
        io.load_vector(2, tmp_path)

"""Distributed restarted GMRES (models/gmres.py): the strategies' matvec
inside the general-matrix Krylov solver — nonsymmetric systems CG cannot
touch, CGS2 Arnoldi, one compiled program, true-residual restarts.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from matvec_mpi_multiplier_tpu import get_strategy, make_mesh
from matvec_mpi_multiplier_tpu.models.gmres import build_gmres, solve_gmres


def _nonsym_system(n, seed=0, shift=2.0):
    """A well-conditioned, deliberately NONSYMMETRIC system: G/sqrt(n)
    keeps the spectrum in a unit-ish disk, the shift pushes it away from
    the origin (GMRES convergence needs 0 outside the field of values)."""
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)) / np.sqrt(n) + shift * np.eye(n)
    assert not np.allclose(a, a.T)  # the point of the module
    x_true = rng.standard_normal(n)
    return a.astype(np.float64), x_true, (a @ x_true).astype(np.float64)


@pytest.mark.parametrize(
    "name", ["rowwise", "colwise", "blockwise", "colwise_ring"]
)
def test_gmres_converges_every_strategy(devices, name):
    a, x_true, b = _nonsym_system(64, seed=1)
    mesh = make_mesh(8)
    res = solve_gmres(
        get_strategy(name), mesh, jnp.asarray(a), jnp.asarray(b), tol=1e-10
    )
    assert bool(res.converged)
    np.testing.assert_allclose(np.asarray(res.x), x_true, rtol=1e-7, atol=1e-7)


def test_gmres_full_krylov_is_direct(devices):
    # With restart >= n, GMRES(m) is plain GMRES: by the Krylov bound it
    # must converge within one cycle on any nonsingular system.
    a, x_true, b = _nonsym_system(32, seed=2)
    mesh = make_mesh(4)
    res = solve_gmres(
        get_strategy("rowwise"), mesh, jnp.asarray(a), jnp.asarray(b),
        tol=1e-10, restart=32,
    )
    assert bool(res.converged)
    assert int(res.n_iters) == 1
    np.testing.assert_allclose(np.asarray(res.x), x_true, rtol=1e-7, atol=1e-7)


def test_gmres_reported_residual_is_true(devices):
    a, _, b = _nonsym_system(48, seed=3)
    mesh = make_mesh(8)
    res = solve_gmres(
        get_strategy("blockwise"), mesh, jnp.asarray(a), jnp.asarray(b),
        tol=1e-8, restart=12,
    )
    true_r = np.linalg.norm(b - a @ np.asarray(res.x))
    # The convergence decision recomputes b - A x each cycle, so the
    # reported norm IS a true residual of the returned iterate.
    np.testing.assert_allclose(float(res.residual_norm), true_r,
                               rtol=1e-6, atol=1e-12)
    assert true_r <= 1e-8 * np.linalg.norm(b)


def test_gmres_max_restarts_cap(devices):
    # An indefinite rotation-heavy system at a tiny restart stalls; the
    # cap must bind, converged must be honest, and the returned iterate
    # must be the best visited (no worse than the zero start).
    rng = np.random.default_rng(4)
    q, _ = np.linalg.qr(rng.standard_normal((48, 48)))
    a = q  # orthogonal: eigenvalues on the unit circle around 0
    b = rng.standard_normal(48)
    mesh = make_mesh(8)
    res = solve_gmres(
        get_strategy("rowwise"), mesh, jnp.asarray(a), jnp.asarray(b),
        tol=1e-14, restart=2, max_restarts=3,
    )
    assert int(res.n_iters) == 3
    assert not bool(res.converged)
    assert float(res.residual_norm) <= np.linalg.norm(b) * (1 + 1e-6)


def test_gmres_fp32_storage_fp32_accuracy(devices):
    a64, x_true, b64 = _nonsym_system(64, seed=5)
    mesh = make_mesh(8)
    res = solve_gmres(
        get_strategy("colwise"), mesh,
        jnp.asarray(a64.astype(np.float32)),
        jnp.asarray(b64.astype(np.float32)), tol=1e-5,
    )
    assert bool(res.converged)
    assert res.x.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(res.x), x_true, rtol=2e-4,
                               atol=2e-4)


def test_gmres_matches_cg_on_spd(devices):
    # On an SPD system both solvers must land on the same answer.
    from matvec_mpi_multiplier_tpu.models.cg import solve_cg

    rng = np.random.default_rng(6)
    g = rng.standard_normal((64, 64))
    a = g.T @ g / 64 + np.eye(64)
    b = rng.standard_normal(64)
    mesh = make_mesh(8)
    strat = get_strategy("rowwise")
    xg = solve_gmres(strat, mesh, jnp.asarray(a), jnp.asarray(b), tol=1e-10)
    xc = solve_cg(strat, mesh, jnp.asarray(a), jnp.asarray(b), tol=1e-10)
    np.testing.assert_allclose(np.asarray(xg.x), np.asarray(xc.x),
                               rtol=1e-7, atol=1e-7)


def test_gmres_zero_rhs(devices):
    a, _, _ = _nonsym_system(32, seed=7)
    mesh = make_mesh(4)
    res = solve_gmres(
        get_strategy("rowwise"), mesh, jnp.asarray(a),
        jnp.zeros(32, jnp.float64),
    )
    assert bool(res.converged)
    assert int(res.n_iters) == 0
    np.testing.assert_array_equal(np.asarray(res.x), np.zeros(32))


def test_refined_gmres_beats_plain_fp32_on_nonsym_illconditioned(devices):
    """Wilkinson refinement with a GMRES inner solver on an fp32
    NONSYMMETRIC system at cond ~1e4 (a row-scaled triangular matrix —
    eigenvalues = its positive diagonal, so full-Krylov GMRES is
    direct-grade). Restarted GMRES already self-refines (each restart
    re-solves the residual system), so plain fp32 floors at the fp32
    RESIDUAL-EVALUATION precision ~u*||A||*||x||, not at cond*u; the
    refined solver's fp64-parity (ozaki) residuals + double-float x push
    an order of magnitude below that floor. Accuracy judged against the
    fp64 solve of the ROUNDED system, as in the CG refinement test."""
    from matvec_mpi_multiplier_tpu.models.cg import build_refined

    n = 96
    rng = np.random.default_rng(8)
    u = np.triu(rng.standard_normal((n, n)), 1)
    a64 = np.diag(np.logspace(0, -4, n)) @ (np.eye(n) + 0.02 * u)
    assert 1e3 < np.linalg.cond(a64) < 1e5
    assert not np.allclose(a64, a64.T)
    a32 = a64.astype(np.float32)
    b32 = (a64 @ rng.standard_normal(n)).astype(np.float32)
    xs = np.linalg.solve(a32.astype(np.float64), b32.astype(np.float64))
    mesh = make_mesh(8)
    strat = get_strategy("rowwise")
    rel = lambda x: float(
        np.max(np.abs(np.asarray(x, np.float64) - xs)) / np.max(np.abs(xs))
    )

    plain = solve_gmres(strat, mesh, jnp.asarray(a32), jnp.asarray(b32),
                        tol=1e-12, restart=n, max_restarts=20)
    refined = build_refined(strat, mesh, inner="gmres", restart=n)(
        jnp.asarray(a32), jnp.asarray(b32)
    )
    assert bool(refined.converged)
    assert rel(refined.x) < 1e-7           # below the fp32 residual floor
    assert rel(refined.x) * 4 < rel(plain.x)  # measured ~10x at seed 8


def test_refined_gmres_defaults_to_small_inner_restart(devices, monkeypatch):
    """The loose inner solves (inner_tol=1e-2) need a few digits per trip,
    and GMRES(m) has no in-cycle exit — every trip pays all m matvecs. The
    refinement default must therefore be a small restart (ADVICE round 5),
    while an explicit restart= passes through untouched."""
    import matvec_mpi_multiplier_tpu.models.gmres as gmres_mod
    from matvec_mpi_multiplier_tpu.models.cg import build_refined

    seen = []
    real = gmres_mod.build_gmres

    def spy(strategy, mesh, **kw):
        seen.append(kw)
        return real(strategy, mesh, **kw)

    monkeypatch.setattr(gmres_mod, "build_gmres", spy)
    mesh = make_mesh(8)
    strat = get_strategy("rowwise")
    build_refined(strat, mesh, inner="gmres")
    assert seen[-1]["restart"] == 10
    build_refined(strat, mesh, inner="gmres", restart=64)
    assert seen[-1]["restart"] == 64


def test_refined_rejects_unknown_inner(devices):
    from matvec_mpi_multiplier_tpu.models.cg import build_refined

    with pytest.raises(ValueError, match="inner"):
        build_refined(get_strategy("rowwise"), make_mesh(8), inner="qmr")


def test_gmres_cli_smoke(monkeypatch, capsys):
    from pathlib import Path
    import sys  # noqa: F401  (pattern parity with test_cg_cli_smoke)

    monkeypatch.syspath_prepend(
        str(Path(__file__).parents[1] / "scripts")
    )
    import solve_cg

    rc = solve_cg.main([
        "--size", "64", "--method", "gmres", "--strategy", "rowwise",
        "--devices", "4", "--tol", "1e-6",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "gmres[rowwise" in out and "converged=True" in out


def test_gmres_guards(devices):
    mesh = make_mesh(8)
    with pytest.raises(ValueError, match="square"):
        build_gmres(get_strategy("rowwise"), mesh)(
            jnp.ones((16, 8)), jnp.ones(8)
        )
    with pytest.raises(ValueError, match="restart"):
        build_gmres(get_strategy("rowwise"), mesh, restart=0)

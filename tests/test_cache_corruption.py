"""Tuning-cache persistence-corruption coverage (ISSUE 7 satellite).

Pins the ``cache.py`` load() catch the serving stack leans on — a corrupt
``tuning_cache.json`` must never break a sweep or an engine construction
— and the quarantine contract: an existed-but-unusable file loads as
empty AND is preserved as ``tuning_cache.json.corrupt`` by the next
``save()`` instead of being silently overwritten (postmortem evidence).
"""

import json

import pytest

from matvec_mpi_multiplier_tpu.tuning.cache import (
    CACHE_VERSION,
    TuningCache,
)


def _valid_payload():
    return {
        "version": CACHE_VERSION,
        "entries": {"fp|gemv|8x8|float32": {"kernel": "xla", "time_s": 1e-5}},
    }


@pytest.fixture()
def cache_file(tmp_path):
    return tmp_path / "tuning_cache.json"


def test_valid_file_loads_and_is_not_quarantined(cache_file):
    cache_file.write_text(json.dumps(_valid_payload()))
    cache = TuningCache.load(cache_file)
    assert len(cache) == 1
    assert not cache.quarantined
    cache.save()
    assert not cache.corrupt_path.exists()


def test_missing_file_is_empty_but_not_quarantined(cache_file):
    cache = TuningCache.load(cache_file)
    assert len(cache) == 0
    assert not cache.quarantined
    cache.save()  # nothing to preserve
    assert not cache.corrupt_path.exists()
    assert cache_file.exists()


@pytest.mark.parametrize(
    "payload",
    [
        "",                                   # empty file
        "{\"version\": 3, \"entr",            # truncated mid-write
        "not json at all {{{",                # garbage bytes
        json.dumps([1, 2, 3]),                # parseable, wrong shape
        json.dumps({"version": 99, "entries": {}}),   # unknown version
        json.dumps({"version": CACHE_VERSION, "entries": "nope"}),
    ],
    ids=["empty", "truncated", "garbage", "non-dict", "future-version",
         "bad-entries"],
)
def test_unusable_file_loads_empty_and_quarantined(cache_file, payload):
    cache_file.write_text(payload)
    cache = TuningCache.load(cache_file)
    assert len(cache) == 0
    assert cache.quarantined
    # lookup behaves exactly like a cold cache (static-default fallback)
    assert cache.lookup("anything") is None


def test_save_preserves_corrupt_file_for_postmortem(cache_file):
    corrupt_bytes = "{\"version\": 3, \"entr"  # the crash-truncated file
    cache_file.write_text(corrupt_bytes)
    cache = TuningCache.load(cache_file)
    assert cache.quarantined
    cache.record("fp|gemv|4x4|float32", {"kernel": "xla"})
    cache.save()
    # the evidence moved aside, byte-identical
    assert cache.corrupt_path.read_text() == corrupt_bytes
    # the live file is a fresh, valid cache with the new decision
    reloaded = TuningCache.load(cache_file)
    assert not reloaded.quarantined
    assert reloaded.lookup("fp|gemv|4x4|float32") == {"kernel": "xla"}
    # a second save neither re-quarantines nor disturbs the evidence
    cache.save()
    assert cache.corrupt_path.read_text() == corrupt_bytes


def test_repeated_quarantine_keeps_most_recent_evidence(cache_file):
    cache_file.write_text("first corruption")
    TuningCache.load(cache_file).save()
    cache_file.write_text("second corruption")
    TuningCache.load(cache_file).save()
    assert TuningCache.load(cache_file).quarantined is False
    cache = TuningCache(cache_file)
    assert cache.corrupt_path.read_text() == "second corruption"


def test_save_survives_corrupt_file_vanishing(cache_file):
    cache_file.write_text("garbage {{{")
    cache = TuningCache.load(cache_file)
    assert cache.quarantined
    cache_file.unlink()  # raced away between load and save
    cache.save()  # must not raise
    assert not cache.corrupt_path.exists()
    assert json.loads(cache_file.read_text())["version"] == CACHE_VERSION

"""Tuning-cache persistence-corruption coverage (ISSUE 7 satellite).

Pins the ``cache.py`` load() catch the serving stack leans on — a corrupt
``tuning_cache.json`` must never break a sweep or an engine construction
— and the quarantine contract: an existed-but-unusable file loads as
empty AND is preserved as ``tuning_cache.json.corrupt`` by the next
``save()`` instead of being silently overwritten (postmortem evidence).
"""

import json

import pytest

from matvec_mpi_multiplier_tpu.tuning.cache import (
    CACHE_VERSION,
    TuningCache,
)


def _valid_payload():
    return {
        "version": CACHE_VERSION,
        "entries": {"fp|gemv|8x8|float32": {"kernel": "xla", "time_s": 1e-5}},
    }


@pytest.fixture()
def cache_file(tmp_path):
    return tmp_path / "tuning_cache.json"


def test_valid_file_loads_and_is_not_quarantined(cache_file):
    cache_file.write_text(json.dumps(_valid_payload()))
    cache = TuningCache.load(cache_file)
    assert len(cache) == 1
    assert not cache.quarantined
    cache.save()
    assert not cache.corrupt_path.exists()


def test_missing_file_is_empty_but_not_quarantined(cache_file):
    cache = TuningCache.load(cache_file)
    assert len(cache) == 0
    assert not cache.quarantined
    cache.save()  # nothing to preserve
    assert not cache.corrupt_path.exists()
    assert cache_file.exists()


@pytest.mark.parametrize(
    "payload",
    [
        "",                                   # empty file
        "{\"version\": 3, \"entr",            # truncated mid-write
        "not json at all {{{",                # garbage bytes
        json.dumps([1, 2, 3]),                # parseable, wrong shape
        json.dumps({"version": 99, "entries": {}}),   # unknown version
        json.dumps({"version": CACHE_VERSION, "entries": "nope"}),
    ],
    ids=["empty", "truncated", "garbage", "non-dict", "future-version",
         "bad-entries"],
)
def test_unusable_file_loads_empty_and_quarantined(cache_file, payload):
    cache_file.write_text(payload)
    cache = TuningCache.load(cache_file)
    assert len(cache) == 0
    assert cache.quarantined
    # lookup behaves exactly like a cold cache (static-default fallback)
    assert cache.lookup("anything") is None


def test_save_preserves_corrupt_file_for_postmortem(cache_file):
    corrupt_bytes = "{\"version\": 3, \"entr"  # the crash-truncated file
    cache_file.write_text(corrupt_bytes)
    cache = TuningCache.load(cache_file)
    assert cache.quarantined
    cache.record("fp|gemv|4x4|float32", {"kernel": "xla"})
    cache.save()
    # the evidence moved aside, byte-identical
    assert cache.corrupt_path.read_text() == corrupt_bytes
    # the live file is a fresh, valid cache with the new decision
    reloaded = TuningCache.load(cache_file)
    assert not reloaded.quarantined
    assert reloaded.lookup("fp|gemv|4x4|float32") == {"kernel": "xla"}
    # a second save neither re-quarantines nor disturbs the evidence
    cache.save()
    assert cache.corrupt_path.read_text() == corrupt_bytes


def test_repeated_quarantine_keeps_most_recent_evidence(cache_file):
    cache_file.write_text("first corruption")
    TuningCache.load(cache_file).save()
    cache_file.write_text("second corruption")
    TuningCache.load(cache_file).save()
    assert TuningCache.load(cache_file).quarantined is False
    cache = TuningCache(cache_file)
    assert cache.corrupt_path.read_text() == "second corruption"


def test_save_survives_corrupt_file_vanishing(cache_file):
    cache_file.write_text("garbage {{{")
    cache = TuningCache.load(cache_file)
    assert cache.quarantined
    cache_file.unlink()  # raced away between load and save
    cache.save()  # must not raise
    assert not cache.corrupt_path.exists()
    assert json.loads(cache_file.read_text())["version"] == CACHE_VERSION


def test_v1_through_v5_caches_still_load_under_v6(cache_file):
    """Schema-bump back-compat (ISSUE 8, extended by ISSUE 10's v5 and
    ISSUE 17's v6): every historical version's entries are strict
    subsets of v6's — an old cache keeps serving its decisions instead
    of forcing a silent full re-tune."""
    old_entries = {
        1: {"fp|gemv|8x8|float32": {"kernel": "xla", "time_s": 1e-5}},
        2: {"fp|promote|rowwise|8x8|p2|float32": {"b_star": 4}},
        3: {"fp|overlap|rowwise|8x8|p2|float32": {"stages": 2}},
        4: {"fp|storage|rowwise|8x8|p2|float32": {
            "storage": "int8", "resident_bytes": {"int8": 80},
        }},
        5: {"fp|calibration|p2": {"flops": 1e10}},
    }
    assert CACHE_VERSION == 6
    for version, entries in old_entries.items():
        cache_file.write_text(
            json.dumps({"version": version, "entries": entries})
        )
        cache = TuningCache.load(cache_file)
        assert not cache.quarantined, f"v{version} wrongly quarantined"
        for key, decision in entries.items():
            assert cache.lookup(key) == decision


def test_v5_calibration_record_round_trips(cache_file):
    """The v5 calibration kind (the cost model's machine constants —
    tuning/cost_model.py) persists and reloads intact alongside ordinary
    decisions, and rebuilds into a usable model."""
    from matvec_mpi_multiplier_tpu.tuning.cache import calibration_key
    from matvec_mpi_multiplier_tpu.tuning.cost_model import (
        Calibration,
        model_from_cache,
    )

    cal = Calibration(
        flops=1e11, mem_bps=2e10,
        alpha_s={"collective": 5e-4, "permute": 4e-4},
        beta_bps={"collective": 7e8, "permute": 7e8},
        p=8, level="full", probes={"gemv_s": 1e-3},
    )
    cache = TuningCache.load(cache_file)
    cache.record(calibration_key(8, "fp"), cal.to_record())
    cache.record("fp|gemv|8x8|float32", {"kernel": "xla"})
    cache.save()

    reloaded = TuningCache.load(cache_file)
    assert Calibration.from_record(
        reloaded.lookup(calibration_key(8, "fp"))
    ) == cal
    assert reloaded.lookup("fp|gemv|8x8|float32") == {"kernel": "xla"}
    model = model_from_cache(reloaded, 8, fingerprint="fp")
    assert model is not None and model.calibration.p == 8


def test_future_version_preserved_in_versioned_slot(cache_file):
    """A shape-valid FUTURE-schema file is someone's data, not damage: it
    must park under its own ``.v<N>.corrupt`` slot, where a later
    truncated-write quarantine (generic ``.corrupt``) cannot clobber it."""
    future = json.dumps({
        "version": 99,
        "entries": {"fp|holo|8x8|float32": {"kernel": "quantum"}},
    })
    cache_file.write_text(future)
    cache = TuningCache.load(cache_file)
    assert cache.quarantined and len(cache) == 0
    cache.save()
    versioned = cache_file.with_name(cache_file.name + ".v99.corrupt")
    assert versioned.read_text() == future
    # The live file is a fresh v4 cache.
    assert json.loads(cache_file.read_text())["version"] == CACHE_VERSION

    # Now ordinary corruption arrives and gets quarantined too — into the
    # GENERIC slot; the future build's bytes survive untouched.
    cache_file.write_text("{\"version\": 4, \"entr")
    TuningCache.load(cache_file).save()
    generic = cache_file.with_name(cache_file.name + ".corrupt")
    assert generic.read_text() == "{\"version\": 4, \"entr"
    assert versioned.read_text() == future


def test_nonsense_version_stays_in_generic_slot(cache_file):
    """A version field that is not an int (or entries that are not a
    dict) is damage, not a future schema — generic slot."""
    for payload in (
        json.dumps({"version": "banana", "entries": {}}),
        json.dumps({"version": 99, "entries": "nope"}),
    ):
        cache_file.write_text(payload)
        cache = TuningCache.load(cache_file)
        assert cache.quarantined
        assert cache.corrupt_path == cache_file.with_name(
            cache_file.name + ".corrupt"
        )

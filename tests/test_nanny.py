"""Unit tests for scripts/capture_nanny.sh's decision helpers.

The nanny is the last link in the capture chain (nanny -> watcher ->
tpu_measure_all.py -> stages): it SIGKILLs and relaunches a capture whose
process tree stops advancing CPU (the tunnel-wedge signature, see the
script header). A wrong pid walk or tick sum kills healthy captures, so
the helpers get the same unit treatment as the Python plumbing
(tests/test_aux.py::test_tpu_measure_all_stage_plumbing).

Each test sources just the function under test out of the script with sed
and drives it against this test's own live process tree — real /proc, no
mocks of the kernel interface.
"""

import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
NANNY = REPO / "scripts" / "capture_nanny.sh"


def _run_with_helpers(body: str) -> subprocess.CompletedProcess:
    """Run a bash snippet with the nanny's helper functions in scope."""
    script = (
        f'source <(sed -n "/^descendants()/,/^}}/p; /^ticks_of()/,/^}}/p; '
        f'/^capture_up()/,/^}}/p" {NANNY})\n' + body
    )
    return subprocess.run(
        ["bash", "-c", script], capture_output=True, text=True, timeout=60
    )


def test_descendants_walks_grandchildren():
    # bash parent -> bash child -> sleep grandchild: the walk must find all
    # three levels, since sweep stages are grandchildren of the watcher.
    r = _run_with_helpers(
        "gcf=$(mktemp)\n"
        'bash -c "sleep 30 & echo \\$! > $gcf; wait" & c=$!\n'
        "sleep 0.5\n"
        "d=$(descendants $$)\n"
        'gc=$(cat "$gcf"); rm -f "$gcf"\n'
        "kill $c $gc 2>/dev/null\n"
        'case " $d " in *" $c "*) ;; *) echo MISSING-CHILD; exit 1;; esac\n'
        'case " $d " in *" $gc "*) ;; *) echo MISSING-GRANDCHILD; exit 1;; esac\n'
        "echo OK"
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


def test_descendants_includes_root_and_ignores_strangers():
    r = _run_with_helpers(
        "d=$(descendants $$)\n"
        'case " $d " in *" $$ "*) ;; *) echo MISSING-ROOT; exit 1;; esac\n'
        # pid 1 is never in this shell's subtree
        'case " $d " in *" 1 "*) echo STRANGER; exit 1;; *) ;; esac\n'
        "echo OK"
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_ticks_monotone_across_child_exit():
    # The wedge detector's core invariant: when a CPU-burning child exits,
    # its ticks must persist in the parent's cutime (summed by ticks_of),
    # so the aggregate cannot collapse and fake a stall-window reset/trip.
    r = _run_with_helpers(
        # burn ~0.3s CPU in a child, measure while alive
        "bash -c 'i=0; while [ $i -lt 300000 ]; do i=$((i+1)); done' & c=$!\n"
        "wait $c\n"
        "after=$(ticks_of $(descendants $$))\n"
        'echo "after=$after"\n'
        "[ \"$after\" -ge 10 ] || { echo LOST-CHILD-TICKS; exit 1; }\n"
        "echo OK"
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_ticks_of_survives_vanished_pid():
    r = _run_with_helpers("ticks_of 999999 $$ >/dev/null && echo OK")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


def test_capture_up_detects_orchestrator_cmdline():
    # capture_up keys on the orchestrator script name in /proc cmdline;
    # a probing watcher (no orchestrator child) must read as "down".
    # The orchestrator name is spelled split so THIS test shell's own
    # cmdline (which embeds this script text) can't satisfy capture_up —
    # the same self-match trap the nanny avoids by walking descendants.
    r = _run_with_helpers(
        'name="tpu_measure_""all.py"\n'
        "sleep 30 & plain=$!\n"
        "if capture_up $plain; then echo FALSE-POSITIVE; "
        "kill $plain; exit 1; fi\n"
        'python3 -c "import time; time.sleep(30)" "$name" & cap=$!\n'
        "sleep 0.5\n"
        "capture_up $plain $cap; rc=$?\n"
        "kill $plain $cap 2>/dev/null\n"
        "[ $rc -eq 0 ] || { echo MISSED-CAPTURE; exit 1; }\n"
        "echo OK"
    )
    assert r.returncode == 0, r.stdout + r.stderr


def _run_nanny_with_stub_watcher(
    tmp_path, stub_body: str, timeout=45, extra_env=None
):
    """Run the real nanny against a stub watch_and_capture.sh in an
    isolated tree (the nanny cd's to its script's parent dir)."""
    import os
    import shutil

    scripts = tmp_path / "scripts"
    scripts.mkdir()
    shutil.copy(NANNY, scripts / "capture_nanny.sh")
    stub = scripts / "watch_and_capture.sh"
    stub.write_text("#!/bin/bash\n" + stub_body)
    env = dict(
        os.environ,
        NANNY_POLL_S="1",
        NANNY_MAX_RESTARTS="2",
        NANNY_CAPTURE_LOG=str(tmp_path / "cap.log"),
        **(extra_env or {}),
    )
    return subprocess.run(
        ["bash", str(scripts / "capture_nanny.sh")],
        env=env, capture_output=True, text=True, timeout=timeout,
    )


@pytest.mark.parametrize("rc", [0, 1, 2])
def test_voluntary_watcher_exit_stops_nanny(tmp_path, rc):
    # rc 0/1/2 are the watcher's three voluntary exits (complete / attempt
    # budget / deterministic failure): the nanny must stop, forward the
    # code, and never restart.
    r = _run_nanny_with_stub_watcher(tmp_path, f"exit {rc}\n")
    assert r.returncode == rc, r.stdout + r.stderr
    assert "nanny done" in r.stdout
    assert "restarting" not in r.stdout


@pytest.mark.parametrize("rc", [126, 127])
def test_exec_failure_is_fatal_not_retried(tmp_path, rc):
    # rc 126 (not executable) / 127 (not found) are deterministic launch
    # failures: retrying the identical command line MAX_RESTARTS times
    # cannot fix a missing or chmod-less script, so the nanny must forward
    # the code immediately instead of burning its restart budget.
    r = _run_nanny_with_stub_watcher(tmp_path, f"exit {rc}\n")
    assert r.returncode == rc, r.stdout + r.stderr
    assert "deterministic exec failure" in r.stdout
    assert "restarting" not in r.stdout


def test_wedge_detection_kills_and_restarts(tmp_path):
    # Full-loop wedge drill: a stub watcher whose "orchestrator" child
    # (cmdline carries tpu_measure_all.py, so capture_up sees a capture)
    # blocks at zero CPU — the wedge signature. With a 3s stall window the
    # nanny must detect it, SIGKILL the family, relaunch, re-detect, and
    # exit 1 when its 2-restart budget runs out.
    r = _run_nanny_with_stub_watcher(
        tmp_path,
        'python3 -c "import time; time.sleep(300)" tpu_measure_all.py &\n'
        "wait\n",
        timeout=120,
        extra_env={"NANNY_STALL_S": "3"},
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert r.stdout.count("WEDGE") == 2, r.stdout
    assert "restart budget exhausted" in r.stdout


def test_involuntary_watcher_death_restarts(tmp_path):
    # A signal death (rc 128+9) is involuntary: the nanny restarts the
    # watcher until its own budget (2 here) runs out, then exits 1.
    r = _run_nanny_with_stub_watcher(tmp_path, "kill -9 $$\n")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "died involuntarily" in r.stdout
    assert "restart budget exhausted" in r.stdout


def test_nanny_script_has_no_global_cmdline_kill():
    """Regression guard: the nanny must scope kills to the watcher's
    descendant tree, never pkill/pgrep by global cmdline pattern (which
    once matched the operator's own shell and unrelated editors)."""
    text = NANNY.read_text()
    assert "pkill" not in text
    assert "pgrep" not in text

"""Distributed GEMM tests: the sharding ladder applied to C = A @ B."""

import jax.numpy as jnp
import numpy as np
import pytest

from matvec_mpi_multiplier_tpu import make_mesh
from matvec_mpi_multiplier_tpu.models.gemm import (
    available_gemm_strategies,
    build_gemm,
    gemm_shardings,
    validate_gemm,
)
from matvec_mpi_multiplier_tpu.utils.errors import ShardingError


def test_registry():
    assert available_gemm_strategies() == ["blockwise", "colwise", "rowwise"]
    with pytest.raises(KeyError, match="unknown gemm strategy"):
        build_gemm("diagonal", make_mesh(1))


@pytest.mark.parametrize("name", ["rowwise", "colwise", "blockwise"])
@pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
def test_gemm_oracle(devices, rng, name, n_dev):
    m, k, n = 16, 24, 12
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    mesh = make_mesh(n_dev)
    validate_gemm(name, m, k, n, mesh)
    c = np.asarray(build_gemm(name, mesh)(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(c, a @ b, rtol=1e-10)


@pytest.mark.parametrize("name", ["rowwise", "colwise", "blockwise"])
@pytest.mark.parametrize("dtype,rtol", [("float32", 1e-5), ("bfloat16", 0.05)])
def test_gemm_reduced_precision(devices, rng, name, dtype, rtol):
    m, k, n = 16, 32, 8
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((k, n)).astype(np.float32)
    mesh = make_mesh(8)
    c = build_gemm(name, mesh)(jnp.asarray(a, dtype), jnp.asarray(b, dtype))
    np.testing.assert_allclose(
        np.asarray(c, np.float32), a @ b, rtol=rtol, atol=rtol
    )


def test_gemm_sharded_output(devices, rng):
    from jax.sharding import PartitionSpec as P

    m, k, n = 16, 16, 8
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    mesh = make_mesh(8)
    c = build_gemm("blockwise", mesh, gather_output=False)(
        jnp.asarray(a), jnp.asarray(b)
    )
    # jax normalizes away the trailing None dim in the reported spec.
    assert c.sharding.spec == P("rows")
    np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-10)


def test_gemm_guards(devices):
    mesh = make_mesh(8)  # 2x4
    with pytest.raises(ShardingError, match="m \\(rows of A\\)"):
        validate_gemm("rowwise", 12, 16, 8, mesh)
    with pytest.raises(ShardingError, match="k \\(contraction dim\\)"):
        validate_gemm("colwise", 16, 12, 8, mesh)
    with pytest.raises(ShardingError, match="mesh cols"):
        validate_gemm("blockwise", 16, 10, 8, mesh)


def test_gemm_shardings_placement(devices, rng):
    import jax

    mesh = make_mesh(8)
    sh_a, sh_b = gemm_shardings("blockwise", mesh)
    a = jax.device_put(rng.standard_normal((16, 16)), sh_a)
    b = jax.device_put(rng.standard_normal((16, 8)), sh_b)
    c = build_gemm("blockwise", mesh)(a, b)
    np.testing.assert_allclose(
        np.asarray(c), np.asarray(a) @ np.asarray(b), rtol=1e-10
    )

"""Distributed GEMM tests: the sharding ladder applied to C = A @ B."""

import jax.numpy as jnp
import numpy as np
import pytest

from matvec_mpi_multiplier_tpu import make_mesh
from matvec_mpi_multiplier_tpu.models.gemm import (
    available_gemm_strategies,
    build_gemm,
    gemm_shardings,
    validate_gemm,
)
from matvec_mpi_multiplier_tpu.utils.errors import ShardingError


def test_registry():
    assert available_gemm_strategies() == [
        "blockwise", "colwise", "colwise_a2a", "colwise_overlap",
        "colwise_ring", "colwise_ring_overlap", "rowwise",
    ]
    with pytest.raises(KeyError, match="unknown gemm strategy"):
        build_gemm("diagonal", make_mesh(1))


@pytest.mark.parametrize(
    "name",
    ["rowwise", "colwise", "blockwise", "colwise_ring",
     "colwise_ring_overlap"],
)
@pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
def test_gemm_oracle(devices, rng, name, n_dev):
    m, k, n = 16, 24, 12
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    mesh = make_mesh(n_dev)
    validate_gemm(name, m, k, n, mesh)
    c = np.asarray(build_gemm(name, mesh)(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(c, a @ b, rtol=1e-10)


def test_gemm_ring_equals_psum_scatter_schedule(devices, rng):
    # The explicit ring combine must be bit-equivalent (fp64) to computing
    # the full partial and psum-scattering it — same proof the matvec ring
    # carries in tests/test_ring.py.
    m, k, n = 16, 32, 8
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    mesh = make_mesh(8)
    ring = build_gemm("colwise_ring", mesh)(jnp.asarray(a), jnp.asarray(b))
    overlap = build_gemm("colwise_ring_overlap", mesh)(
        jnp.asarray(a), jnp.asarray(b)
    )
    colwise = build_gemm("colwise", mesh)(jnp.asarray(a), jnp.asarray(b))
    np.testing.assert_array_equal(np.asarray(ring), np.asarray(overlap))
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(colwise), rtol=1e-12
    )


def test_gemm_ring_guards(devices):
    mesh = make_mesh(8)
    with pytest.raises(ShardingError, match="k \\(contraction dim\\)"):
        validate_gemm("colwise_ring", 16, 12, 8, mesh)
    with pytest.raises(ShardingError, match="m \\(rows of A\\)"):
        validate_gemm("colwise_ring", 12, 16, 8, mesh)


def test_gemm_ring_sharded_output(devices, rng):
    from jax.sharding import PartitionSpec as P

    m, k, n = 16, 16, 8
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    mesh = make_mesh(8)
    c = build_gemm("colwise_ring", mesh, gather_output=False)(
        jnp.asarray(a), jnp.asarray(b)
    )
    assert c.sharding.spec == P(("rows", "cols"))  # C rows ride the ring
    np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-10)


@pytest.mark.parametrize("name", ["rowwise", "colwise", "blockwise"])
@pytest.mark.parametrize("dtype,rtol", [("float32", 1e-5), ("bfloat16", 0.05)])
def test_gemm_reduced_precision(devices, rng, name, dtype, rtol):
    m, k, n = 16, 32, 8
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((k, n)).astype(np.float32)
    mesh = make_mesh(8)
    c = build_gemm(name, mesh)(jnp.asarray(a, dtype), jnp.asarray(b, dtype))
    np.testing.assert_allclose(
        np.asarray(c, np.float32), a @ b, rtol=rtol, atol=rtol
    )


def test_gemm_sharded_output(devices, rng):
    from jax.sharding import PartitionSpec as P

    m, k, n = 16, 16, 8
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    mesh = make_mesh(8)
    c = build_gemm("blockwise", mesh, gather_output=False)(
        jnp.asarray(a), jnp.asarray(b)
    )
    # jax normalizes away the trailing None dim in the reported spec.
    assert c.sharding.spec == P("rows")
    np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-10)


def test_gemm_guards(devices):
    mesh = make_mesh(8)  # 2x4
    with pytest.raises(ShardingError, match="m \\(rows of A\\)"):
        validate_gemm("rowwise", 12, 16, 8, mesh)
    with pytest.raises(ShardingError, match="k \\(contraction dim\\)"):
        validate_gemm("colwise", 16, 12, 8, mesh)
    with pytest.raises(ShardingError, match="mesh cols"):
        validate_gemm("blockwise", 16, 10, 8, mesh)


def test_gemm_shardings_placement(devices, rng):
    import jax

    mesh = make_mesh(8)
    sh_a, sh_b = gemm_shardings("blockwise", mesh)
    a = jax.device_put(rng.standard_normal((16, 16)), sh_a)
    b = jax.device_put(rng.standard_normal((16, 8)), sh_b)
    c = build_gemm("blockwise", mesh)(a, b)
    np.testing.assert_allclose(
        np.asarray(c), np.asarray(a) @ np.asarray(b), rtol=1e-10
    )


def test_gemm_kernel_registry():
    from matvec_mpi_multiplier_tpu.ops import available_gemm_kernels, get_gemm_kernel

    assert "xla" in available_gemm_kernels()
    assert "pallas" in available_gemm_kernels()
    with pytest.raises(KeyError, match="unknown gemm kernel"):
        get_gemm_kernel("nope")


def test_pallas_gemm_matches_xla(rng):
    # Tile-aligned shape: exercises the pallas path (interpret mode on CPU)
    # against the XLA kernel.
    from matvec_mpi_multiplier_tpu.ops.pallas_gemm import matmul_pallas

    a = rng.standard_normal((32, 256)).astype(np.float32)
    b = rng.standard_normal((256, 128)).astype(np.float32)
    c = np.asarray(matmul_pallas(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(c, a @ b, rtol=1e-5, atol=1e-5)


def test_pallas_gemm_fallback_unaligned(rng):
    # Shapes without aligned tiles route through the XLA kernel.
    from matvec_mpi_multiplier_tpu.ops.pallas_gemm import matmul_pallas

    a = rng.standard_normal((7, 13)).astype(np.float32)
    b = rng.standard_normal((13, 5)).astype(np.float32)
    c = np.asarray(matmul_pallas(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(c, a @ b, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("name", ["rowwise", "colwise", "blockwise"])
def test_gemm_pallas_kernel_distributed(devices, rng, name):
    # The pallas tier under shard_map on the 8-device mesh. 32-row/128-col
    # tiles divide the local blocks, so the pallas path (not the fallback)
    # runs on every device.
    m, k, n = 64, 512, 128
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((k, n)).astype(np.float32)
    mesh = make_mesh(8)
    c = build_gemm(name, mesh, kernel="pallas")(jnp.asarray(a), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-4, atol=1e-4)


def test_benchmark_gemm_result(devices, rng, tmp_path):
    from matvec_mpi_multiplier_tpu.bench.metrics import append_result, csv_path, read_csv
    from matvec_mpi_multiplier_tpu.bench.timing import benchmark_gemm

    a = rng.standard_normal((16, 16))
    b = rng.standard_normal((16, 8))
    res = benchmark_gemm(
        "blockwise", make_mesh(8), a, b, n_reps=2, measure="sync"
    )
    assert res.strategy == "gemm_blockwise"
    assert res.n_rhs == 8
    # FLOPs/bytes account for the rank-2 rhs.
    assert res.gflops == pytest.approx(2 * 16 * 16 * 8 / res.mean_time_s / 1e9)
    path = append_result(res, tmp_path)
    assert path == csv_path("gemm_blockwise", tmp_path)
    rows = read_csv(path)
    assert rows[0]["n_rows"] == 16


def test_sweep_cli_gemm(devices, tmp_path, monkeypatch):
    from matvec_mpi_multiplier_tpu.bench import sweep

    monkeypatch.chdir(tmp_path)
    rc = sweep.main(
        [
            "--op", "gemm", "--strategy", "blockwise", "--sizes", "16",
            "--devices", "8", "--n-rhs", "8", "--n-reps", "2",
            "--measure", "sync",
        ]
    )
    assert rc == 0
    from matvec_mpi_multiplier_tpu.bench.metrics import read_csv

    rows = read_csv(tmp_path / "data" / "out" / "gemm_blockwise.csv")
    assert rows[0]["n_rows"] == 16
    assert rows[0]["n_cols"] == 16
    assert rows[0]["n_processes"] == 8
    assert rows[0]["time"] > 0
    ext = read_csv(tmp_path / "data" / "out" / "results_extended.csv")
    assert ext[0]["strategy"] == "gemm_blockwise"
    assert ext[0]["n_rhs"] == 8


def test_sweep_cli_gemm_rejects_use_files(devices):
    from matvec_mpi_multiplier_tpu.bench import sweep

    with pytest.raises(SystemExit, match="matvec-only"):
        sweep.main(["--op", "gemm", "--use-files", "--sizes", "16"])


def test_sweep_cli_rejects_wrong_registry_kernel(devices):
    # 'compensated' exists in the matvec registry but not the GEMM one (and
    # vice versa for typos): the sweep must fail fast, before any config runs.
    from matvec_mpi_multiplier_tpu.bench import sweep

    with pytest.raises(SystemExit, match="unknown gemm kernel"):
        sweep.main(["--op", "gemm", "--kernel", "compensated", "--sizes", "16"])
    with pytest.raises(SystemExit, match="unknown matvec kernel"):
        sweep.main(["--kernel", "nope", "--sizes", "16"])

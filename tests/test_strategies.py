"""Strategy correctness tests.

The test pyramid the reference lacks (SURVEY.md §4): every strategy is checked
against (a) the committed 4×8 fixture with its derived ground truth
``[222.2, 196.55, 191.57, 232.9]`` and (b) random numpy oracles (``A @ x``),
across device counts {1, 2, 4, 8} on the virtual CPU mesh — the analog of the
reference's ``mpiexec -n p`` sweep — plus the divisibility guards
(with quirks Q2/Q3 fixed, see utils/errors.py).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from matvec_mpi_multiplier_tpu import (
    BlockwiseStrategy,
    ColwiseStrategy,
    RowwiseStrategy,
    ShardingError,
    get_strategy,
    make_mesh,
)

from conftest import FIXTURE_MATRIX, FIXTURE_PRODUCT, FIXTURE_VECTOR

# Every registered strategy — the oracle/dtype shapes below divide evenly
# for all of them at every swept device count; only the 4x8 fixture needs
# constraint-based skips (see test_fixture_4x8).
ALL_STRATEGIES = [
    "rowwise", "colwise", "colwise_ring", "colwise_ring_overlap",
    "colwise_a2a", "colwise_overlap", "blockwise",
]


def run_strategy(name, mesh, a, x, **kwargs):
    strat = get_strategy(name, **kwargs.pop("strategy_kwargs", {}))
    strat.validate(a.shape[0], a.shape[1], mesh)
    fn = strat.build(mesh, **kwargs)
    return np.asarray(fn(jnp.asarray(a), jnp.asarray(x)))


# ---------- fixture ground truth ----------

@pytest.mark.parametrize("name", ALL_STRATEGIES)
@pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
def test_fixture_4x8(devices, fixture_4x8, name, n_dev):
    from matvec_mpi_multiplier_tpu import get_strategy
    from matvec_mpi_multiplier_tpu.utils.errors import ShardingError

    a, x = fixture_4x8
    mesh = make_mesh(n_dev)
    try:
        get_strategy(name).validate(a.shape[0], a.shape[1], mesh)
    except ShardingError as e:
        # The guard working as designed (e.g. 4 rows over 8 devices for the
        # row-scattering strategies); guards themselves are pinned in
        # test_a2a.py / the guard tests below.
        pytest.skip(str(e))
    y = run_strategy(name, mesh, a, x)
    np.testing.assert_allclose(y, FIXTURE_PRODUCT, rtol=1e-12)


# ---------- random oracles across meshes and shapes ----------

@pytest.mark.parametrize("name", ALL_STRATEGIES)
@pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
@pytest.mark.parametrize("shape", [(8, 8), (16, 24), (24, 16)])
def test_random_oracle(devices, rng, name, n_dev, shape):
    a = rng.standard_normal(shape)
    x = rng.standard_normal(shape[1])
    mesh = make_mesh(n_dev)
    y = run_strategy(name, mesh, a, x)
    np.testing.assert_allclose(y, a @ x, rtol=1e-10)


@pytest.mark.parametrize("name", ALL_STRATEGIES)
def test_asymmetric_long_contraction(devices, rng, name):
    """The reference's asymmetric regime: few rows, huge contraction dim
    (120–1200 × 60000 sweep, data/out/asymmetric_*.csv) — scaled down."""
    a = rng.standard_normal((8, 512))
    x = rng.standard_normal(512)
    y = run_strategy(name, make_mesh(8), a, x)
    np.testing.assert_allclose(y, a @ x, rtol=1e-10)


# ---------- output sharding modes ----------

@pytest.mark.parametrize("name", ALL_STRATEGIES)
def test_sharded_output_matches(devices, rng, name):
    a = rng.standard_normal((16, 16))
    x = rng.standard_normal(16)
    mesh = make_mesh(8)
    y = run_strategy(name, mesh, a, x, gather_output=False)
    np.testing.assert_allclose(y, a @ x, rtol=1e-10)


def test_colwise_psum_scatter(devices, rng):
    a = rng.standard_normal((16, 24))
    x = rng.standard_normal(24)
    mesh = make_mesh(8)
    y = run_strategy(
        "colwise", mesh, a, x, strategy_kwargs={"scatter_output": True}
    )
    np.testing.assert_allclose(y, a @ x, rtol=1e-10)


def test_colwise_explicit_scale_sum_kernel(devices, rng):
    """The reference's two-pass colwise kernel formulation
    (src/multiplier_colwise.c:107-122) as an alternative local kernel."""
    a = rng.standard_normal((8, 16))
    x = rng.standard_normal(16)
    y = run_strategy("colwise", make_mesh(4), a, x, kernel="xla_colwise")
    np.testing.assert_allclose(y, a @ x, rtol=1e-10)


# ---------- divisibility guards (Q2/Q3 fixed) ----------

def test_rowwise_guard(devices):
    # reference guard: n_rows % p (src/multiplier_rowwise.c:72-75)
    with pytest.raises(ShardingError, match="n_rows"):
        RowwiseStrategy().validate(10, 8, make_mesh(8))


def test_colwise_guard_names_cols(devices):
    # Q2 fixed: the check is on n_cols and the message must say n_cols
    # (the reference printed "n_rows", src/multiplier_colwise.c:151-153).
    with pytest.raises(ShardingError, match="n_cols"):
        ColwiseStrategy().validate(8, 10, make_mesh(8))


def test_blockwise_guard_exact(devices):
    # Q3 fixed: n_rows*n_cols % p == 0 is NOT sufficient; blockwise on a 2×4
    # grid needs n_rows % 2 == 0 and n_cols % 4 == 0.
    mesh = make_mesh(8)  # 2×4 grid
    strat = BlockwiseStrategy()
    strat.validate(2, 8, mesh)  # fine: 2%2==0, 8%4==0
    with pytest.raises(ShardingError, match="n_cols"):
        # 4*6=24 divisible by 8? no — but pick 8×6: 48 % 8 == 0 yet 6 % 4 != 0,
        # exactly the case the reference's weak guard let through.
        strat.validate(8, 6, mesh)
    with pytest.raises(ShardingError, match="n_rows"):
        strat.validate(3, 8, mesh)


def test_build_validates_at_trace_time(devices):
    """build() must surface ShardingError even when the caller skips
    validate() — bad shapes must not reach shard_map's opaque error."""
    fn = RowwiseStrategy().build(make_mesh(8))
    with pytest.raises(ShardingError, match="n_rows"):
        fn(jnp.ones((10, 8)), jnp.ones(8))


def test_blockwise_needs_2d_mesh(devices):
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_1d_mesh

    with pytest.raises(ShardingError, match="2-D mesh"):
        BlockwiseStrategy().validate(8, 8, make_1d_mesh(4))


# ---------- dtype tier ----------

@pytest.mark.parametrize("name", ALL_STRATEGIES)
@pytest.mark.parametrize("dtype,rtol", [("float32", 1e-5), ("bfloat16", 0.03)])
def test_reduced_precision(devices, rng, name, dtype, rtol):
    """Performance-tier dtypes (bf16/fp32 per BASELINE.json) stay accurate:
    accumulation is fp32 (ops/gemv.py) regardless of storage dtype."""
    a = rng.standard_normal((16, 32)).astype(np.float32)
    x = rng.standard_normal(32).astype(np.float32)
    y = run_strategy(
        name, make_mesh(8), a.astype(dtype), x.astype(dtype)
    )
    np.testing.assert_allclose(
        np.asarray(y, dtype=np.float32), a @ x, rtol=rtol, atol=rtol
    )


def test_kernel_accumulator_contract():
    """Kernels return the accumulator dtype (fp32 for bf16 storage) so the
    strategies' psum never accumulates in the storage format."""
    from matvec_mpi_multiplier_tpu.ops.gemv import gemv_colwise_xla, gemv_xla

    a16 = jnp.ones((8, 8), jnp.bfloat16)
    x16 = jnp.ones((8,), jnp.bfloat16)
    assert gemv_xla(a16, x16).dtype == jnp.float32
    assert gemv_colwise_xla(a16, x16).dtype == jnp.float32
    a64 = jnp.ones((8, 8), jnp.float64)
    assert gemv_xla(a64, jnp.ones((8,), jnp.float64)).dtype == jnp.float64


def test_registry():
    from matvec_mpi_multiplier_tpu import available_strategies

    assert available_strategies() == [
        "blockwise", "colwise", "colwise_a2a", "colwise_overlap",
        "colwise_ring", "colwise_ring_overlap", "rowwise",
    ]
    with pytest.raises(KeyError, match="unknown strategy"):
        get_strategy("diagonal")

"""Benchmark-harness tests: timing protocol, CSV schema, sweep CLI.

The CSV schema assertions pin the reference contract
(``src/multiplier_rowwise.c:86,168``): header
``n_rows, n_cols, n_processes, time``, append-only with write-once header.
"""

import numpy as np
import pytest

from matvec_mpi_multiplier_tpu import get_strategy, make_mesh
from matvec_mpi_multiplier_tpu.bench import (
    TimingResult,
    append_result,
    benchmark_strategy,
    csv_path,
    extended_csv_path,
    read_csv,
)
from matvec_mpi_multiplier_tpu.bench.sweep import (
    ASYMMETRIC_SIZES,
    SQUARE_SIZES,
    build_parser,
    device_counts_available,
    main as sweep_main,
)
from matvec_mpi_multiplier_tpu.utils.errors import ConfigError


def _bench(mesh, name="rowwise", shape=(16, 16), **kw):
    rng = np.random.default_rng(0)
    a = rng.standard_normal(shape)
    x = rng.standard_normal(shape[1])
    return benchmark_strategy(get_strategy(name), mesh, a, x, n_reps=3, **kw)


def test_benchmark_strategy_basic(devices):
    res = _bench(make_mesh(4))
    assert res.n_rows == 16 and res.n_cols == 16
    assert res.n_devices == 4
    assert res.strategy == "rowwise"
    assert res.n_reps == 3
    assert res.measure == "loop"  # amortized auto → device-looped reps
    assert len(res.times_s) == 5  # chain_samples independent slope estimates
    # Slope estimates report the MEDIAN (outlier-robust); sync reports the mean.
    assert res.mean_time_s == pytest.approx(np.median(res.times_s))
    assert res.gflops > 0 and res.gbps > 0


def test_loop_measure_explicit(devices):
    res = _bench(make_mesh(4), measure="loop", chain_samples=2)
    assert res.measure == "loop"
    assert len(res.times_s) == 2
    # Median (= mean_time_s) is guaranteed positive; individual samples may
    # carry visible jitter noise.
    assert res.mean_time_s > 0


def test_looped_wrapper_preserves_operand_and_computes():
    """The fori_loop carry with runtime eps=0 must return the rhs unchanged
    (bit-identical), and a nonzero eps must change it — proving the wrapped
    op is really executed inside the loop, not dead-code-eliminated."""
    import jax.numpy as jnp

    from matvec_mpi_multiplier_tpu.bench.timing import _build_looped

    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.standard_normal((8, 8)))
    x = jnp.asarray(rng.standard_normal(8))
    chained = _build_looped(lambda a_, x_: a_ @ x_)
    out0 = chained(a, x, jnp.asarray(3, jnp.int32), jnp.asarray(0.0, jnp.float32))
    np.testing.assert_array_equal(np.asarray(out0), np.asarray(x))
    out1 = chained(a, x, jnp.asarray(3, jnp.int32), jnp.asarray(1.0, jnp.float32))
    assert not np.array_equal(np.asarray(out1), np.asarray(x))


def test_reference_mode_rejects_loop(devices):
    with pytest.raises(ConfigError, match="loop"):
        _bench(make_mesh(2), mode="reference", measure="loop")


def test_time_fn_looped(devices):
    """bench.py's headline path: device-resident args, device-looped reps."""
    import jax.numpy as jnp

    from matvec_mpi_multiplier_tpu.bench.timing import time_fn_looped

    a = jnp.asarray(np.random.default_rng(0).standard_normal((32, 32)))
    x = jnp.asarray(np.random.default_rng(1).standard_normal(32))
    times = time_fn_looped(lambda a_, x_: a_ @ x_, (a, x), n_reps=4, samples=2)
    assert len(times) == 2
    # Individual samples may be negative (visible jitter); the guarantee —
    # enforced by _loop_slope's TimingError — is a positive median.
    assert np.median(times) > 0


def test_looped_bump_is_nonlinear_in_output():
    """The carry bump must be sum(out**2), not sum(out): a linear reduction
    is algebraically transparent — XLA can rewrite sum(A @ x) as
    dot(colsum(A), x), hoist the loop-invariant colsum(A), and turn every
    "rep" into an O(n) vector dot that never re-reads the matrix (observed
    on the TPU backend as fp32 bandwidths 2x the HBM peak). sum(out**2)
    = x'A'Ax admits no such factoring short of forming A'A. The bump value
    with eps=1 pins the quadratic form."""
    import jax.numpy as jnp

    from matvec_mpi_multiplier_tpu.bench.timing import _build_looped

    rng = np.random.default_rng(2)
    a = jnp.asarray(rng.standard_normal((6, 6)))
    x = jnp.asarray(rng.standard_normal(6))
    chained = _build_looped(lambda a_, x_: a_ @ x_)
    out = chained(a, x, jnp.asarray(1, jnp.int32), jnp.asarray(1.0, jnp.float32))
    expected = np.asarray(x) + float(np.sum(np.square(np.asarray(a) @ np.asarray(x))))
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-12)


def test_grow_spread_expands_until_signal_beats_jitter():
    """With a large fixed dispatch overhead and a tiny per-rep cost, the
    spread must widen until the endpoint delta reaches the target — the
    round-1/2 impossible-CSV failure mode was a spread whose signal was
    smaller than tunnel jitter."""
    from matvec_mpi_multiplier_tpu.bench.timing import _grow_spread

    per_rep = 1e-6
    run = lambda k: 0.05 + per_rep * k  # 50 ms dispatch overhead
    delta, t1, t2 = _grow_spread(run, 5, 50, target_delta_s=0.1)
    assert t2 - t1 >= 0.1
    assert (t2 - t1) / delta == pytest.approx(per_rep, rel=1e-6)


def test_grow_spread_stops_at_max_run_time():
    """A single run hitting the wall-clock cap stops growth immediately —
    growth is driven by measured times, so a heavy kernel can never be asked
    to run an unbounded rep count."""
    from matvec_mpi_multiplier_tpu.bench.timing import _grow_spread

    calls = []

    def run(k):
        calls.append(k)
        return 0.1 * k  # 100 ms per rep: first probe already exceeds cap

    delta, t1, t2 = _grow_spread(run, 1, 4, target_delta_s=1e9, max_run_s=0.3)
    assert delta == 4
    assert max(calls) == 5
    # The min-of-2 repeat is NOT skipped at the cap: a lone dispatch spike
    # must not be able to halt growth at a jitter-dominated spread, so the
    # stop decision always sees the min of two runs.
    assert calls.count(5) == 2


def test_grow_spread_rejects_zero_spread():
    """delta=0 must raise, not loop forever (0*4 == 0 never grows)."""
    from matvec_mpi_multiplier_tpu.bench.timing import _grow_spread

    with pytest.raises(ConfigError, match="spread"):
        _grow_spread(lambda k: 0.01, 1, 0, target_delta_s=0.1)


def test_time_matvec_rejects_nonpositive_n_reps(devices):
    rng = np.random.default_rng(0)
    a, x = rng.standard_normal((16, 16)), rng.standard_normal(16)
    with pytest.raises(ConfigError, match="n_reps"):
        benchmark_strategy(
            get_strategy("rowwise"), make_mesh(2), a, x, n_reps=0,
            measure="loop",
        )


def test_loop_slope_raises_on_unmeasurable_signal(monkeypatch):
    """A median slope <= 0 (jitter bigger than the capped signal) must raise
    TimingError, never emit a clamped pseudo-measurement."""
    import matvec_mpi_multiplier_tpu.bench.timing as timing
    from matvec_mpi_multiplier_tpu.utils.errors import TimingError

    # Fake clock: monotonically DECREASING elapsed per call makes every
    # t2 - t1 negative regardless of rep count.
    ticks = iter(np.cumsum([1.0 - 1e-4 * i for i in range(10000)]))
    monkeypatch.setattr(timing.time, "perf_counter", lambda: next(ticks))
    import jax.numpy as jnp

    a = jnp.ones((4, 4)); x = jnp.ones((4,))
    with pytest.raises(TimingError, match="not measurable"):
        timing._loop_slope(lambda a_, x_: a_ @ x_, a, x, 1, 4, 3)


def test_grow_spread_stops_at_rep_cap():
    from matvec_mpi_multiplier_tpu.bench.timing import _grow_spread

    run = lambda k: 1e-12 * k  # effectively free: only the rep cap can stop it
    delta, _, _ = _grow_spread(
        run, 1, 10, target_delta_s=1.0, rep_cap=1000, max_run_s=10.0
    )
    assert delta == 1000


def test_chain_samples_validation(devices):
    from matvec_mpi_multiplier_tpu.utils.errors import ConfigError

    with pytest.raises(ConfigError, match="chain_samples"):
        _bench(make_mesh(2), chain_samples=0)


def test_benchmark_sync_measure(devices):
    res = _bench(make_mesh(2), measure="sync")
    assert len(res.times_s) == 3  # per-rep times
    assert all(t > 0 for t in res.times_s)


def test_benchmark_bad_measure(devices):
    with pytest.raises(ConfigError, match="measure"):
        _bench(make_mesh(2), measure="guess")


def test_benchmark_reference_mode(devices):
    res = _bench(make_mesh(2), mode="reference")
    assert res.mode == "reference"
    assert all(t > 0 for t in res.times_s)


def test_benchmark_bad_mode(devices):
    with pytest.raises(ConfigError, match="mode"):
        _bench(make_mesh(2), mode="warp")


def test_reference_mode_rejects_chain(devices):
    with pytest.raises(ConfigError, match="chain"):
        _bench(make_mesh(2), mode="reference", measure="chain")


def test_reference_mode_separate_csv(devices, tmp_path):
    res = _bench(make_mesh(2), mode="reference")
    path = append_result(res, tmp_path)
    assert path.name == "rowwise_reference.csv"
    assert not csv_path("rowwise", tmp_path).exists()


def test_timing_result_derived_metrics():
    res = TimingResult(
        n_rows=1000, n_cols=1000, n_devices=1, strategy="rowwise",
        dtype="float64", mode="amortized", measure="sync", mean_time_s=0.001,
        times_s=(0.001,),
    )
    assert res.gflops == pytest.approx(2.0)  # 2e6 flops / 1e-3 s / 1e9
    # 8 bytes * (1e6 + 2e3) elements / 1e-3 s / 1e9
    assert res.gbps == pytest.approx(8 * (1_002_000) / 1e6, rel=1e-6)
    assert res.min_time_s == 0.001


def test_csv_reference_schema(devices, tmp_path):
    res = _bench(make_mesh(2))
    path = append_result(res, tmp_path)
    assert path == csv_path("rowwise", tmp_path)
    lines = path.read_text().splitlines()
    # Byte-identical header to src/multiplier_rowwise.c:86.
    assert lines[0] == "n_rows, n_cols, n_processes, time"
    assert lines[1].startswith("16, 16, 2, ")
    # Append-only, header written once (reference :77-88).
    append_result(res, tmp_path)
    lines = path.read_text().splitlines()
    assert len(lines) == 3
    assert lines[0] == "n_rows, n_cols, n_processes, time"


def test_csv_extended(devices, tmp_path):
    res = _bench(make_mesh(2))
    append_result(res, tmp_path)
    rows = read_csv(extended_csv_path(tmp_path))
    assert rows[0]["strategy"] == "rowwise"
    assert rows[0]["n_devices"] == 2
    assert rows[0]["gflops"] > 0


def test_csv_write_is_main_process_only(devices, tmp_path, monkeypatch):
    # The reference guards its CSV block with rank == MAIN_PROCESS
    # (src/multiplier_rowwise.c:159-170); on a faked non-zero rank no file
    # may be written, or every process of a multi-host run would append a
    # duplicate row.
    import jax

    res = _bench(make_mesh(2))
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    path = append_result(res, tmp_path)
    assert not path.exists()
    assert not extended_csv_path(tmp_path).exists()
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    assert append_result(res, tmp_path).exists()


def test_csv_stale_header_rotated(devices, tmp_path):
    # A pre-existing file written under an older schema must not silently
    # receive misaligned rows: it is rotated to .bak and a fresh file started.
    ext = extended_csv_path(tmp_path)
    ext.parent.mkdir(parents=True, exist_ok=True)
    ext.write_text("old, header\n1, 2\n")
    res = _bench(make_mesh(2))
    append_result(res, tmp_path)
    assert ext.with_suffix(".csv.bak").read_text() == "old, header\n1, 2\n"
    rows = read_csv(ext)
    assert rows[0]["strategy"] == "rowwise"  # fresh file, current schema


def test_read_csv_reference_files():
    """Our parser must read the reference's own committed CSVs, including the
    no-space asymmetric header (quirk Q10)."""
    from pathlib import Path

    if not Path("/root/reference/data/out/rowwise.csv").exists():
        pytest.skip("reference checkout not present in this environment")
    rows = read_csv("/root/reference/data/out/rowwise.csv")
    assert rows[0] == {"n_rows": 600, "n_cols": 600, "n_processes": 1,
                       "time": pytest.approx(0.00101, abs=1e-4)}
    arows = read_csv("/root/reference/data/out/asymmetric_rowwise.csv")
    assert arows[0]["n_cols"] == 60000


def test_sweep_sizes_match_reference():
    # test.sh:8 — 600..10200 step 1200; asymmetric CSVs: 120..1200 x 60000.
    assert SQUARE_SIZES == [600, 1800, 3000, 4200, 5400, 6600, 7800, 9000, 10200]
    assert ASYMMETRIC_SIZES[0] == (120, 60000)
    assert ASYMMETRIC_SIZES[-1] == (1200, 60000)
    assert len(ASYMMETRIC_SIZES) == 10


def test_device_counts(devices):
    assert device_counts_available() == [1, 2, 4, 8]
    assert device_counts_available(max_devices=3) == [1, 2, 3]


def test_sweep_cli_end_to_end(devices, tmp_path, monkeypatch):
    monkeypatch.setenv("MATVEC_DATA_DIR", str(tmp_path))
    rc = sweep_main([
        "--strategy", "rowwise", "--devices", "2", "--sizes", "16",
        "--n-reps", "2", "--dtype", "float64",
    ])
    assert rc == 0
    rows = read_csv(csv_path("rowwise", tmp_path))
    assert rows[0]["n_rows"] == 16 and rows[0]["n_processes"] == 2


def test_sweep_cli_keep_going_survives_backend_errors(
    devices, tmp_path, capsys, monkeypatch
):
    """A transient backend failure in one config must not abort the sweep
    when --keep-going is set (tunneled-TPU capture resilience); without the
    flag it propagates."""
    from matvec_mpi_multiplier_tpu.bench import sweep as sweep_mod

    calls = []
    real = sweep_mod.benchmark_strategy

    def flaky(strategy, mesh, a, x, **kw):
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("UNAVAILABLE: tunnel dropped")
        return real(strategy, mesh, a, x, **kw)

    monkeypatch.setenv("MATVEC_DATA_DIR", str(tmp_path))
    monkeypatch.setattr(sweep_mod, "benchmark_strategy", flaky)
    args = ["--strategy", "rowwise", "--devices", "2", "--sizes", "16", "32",
            "--n-reps", "2", "--dtype", "float64"]
    rc = sweep_main(args + ["--keep-going"])
    # 5, not 1: a COMPLETED sweep with recorded config failures is the
    # retry-worthy class (crashes exit 1, usage errors 2) — the capture
    # orchestrator keys retry-vs-stop off exactly this code.
    assert rc == 5
    assert "FAILED" in capsys.readouterr().err
    rows = read_csv(csv_path("rowwise", tmp_path))
    assert len(rows) == 1 and rows[0]["n_rows"] == 32  # later config landed

    calls.clear()
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
        sweep_main(args)


def test_sweep_cli_keep_going_skips_unmeasurable(
    devices, tmp_path, capsys, monkeypatch
):
    """TimingError (measurement failure) is skippable under --keep-going —
    unlike other MatvecErrors, which are config bugs and abort regardless."""
    from matvec_mpi_multiplier_tpu.bench import sweep as sweep_mod
    from matvec_mpi_multiplier_tpu.utils.errors import TimingError

    calls = []
    real = sweep_mod.benchmark_strategy

    def flaky(strategy, mesh, a, x, **kw):
        calls.append(1)
        if len(calls) == 1:
            raise TimingError("slope not measurable")
        return real(strategy, mesh, a, x, **kw)

    monkeypatch.setenv("MATVEC_DATA_DIR", str(tmp_path))
    monkeypatch.setattr(sweep_mod, "benchmark_strategy", flaky)
    args = ["--strategy", "rowwise", "--devices", "2", "--sizes", "16", "32",
            "--n-reps", "2", "--dtype", "float64"]
    rc = sweep_main(args + ["--keep-going"])
    # rc=3, not 1: unmeasurable-only is a soft outcome — a capture watcher
    # must not burn a healthy window re-running rows that would only re-hit
    # the same noise floor (a hard backend failure still exits 1, and 3
    # rather than 2 keeps argparse usage errors unambiguous).
    assert rc == 3
    assert "UNMEASURABLE" in capsys.readouterr().err
    rows = read_csv(csv_path("rowwise", tmp_path))
    assert len(rows) == 1 and rows[0]["n_rows"] == 32

    calls.clear()
    with pytest.raises(TimingError):
        sweep_main(args)


def test_sweep_cli_skip_measured_resumes(devices, tmp_path, monkeypatch, capsys):
    """--skip-measured: configs whose rows already sit in the extended CSV
    are skipped (the capture-retry resume path after a tunnel wedge), new
    configs still run, and no row is ever duplicated."""
    monkeypatch.setenv("MATVEC_DATA_DIR", str(tmp_path))
    base = ["--strategy", "rowwise", "--devices", "2", "--n-reps", "2",
            "--dtype", "float64", "--measure", "sync"]
    assert sweep_main(base + ["--sizes", "16"]) == 0
    rows1 = read_csv(extended_csv_path(tmp_path))
    assert len(rows1) == 1

    # Identical re-run with --skip-measured: nothing timed, nothing added.
    assert sweep_main(base + ["--sizes", "16", "--skip-measured"]) == 0
    out = capsys.readouterr().out
    assert "already measured" in out
    assert "0 configs timed" in out
    assert read_csv(extended_csv_path(tmp_path)) == rows1

    # A widened sweep resumes: only the new size runs.
    assert sweep_main(base + ["--sizes", "16", "32", "--skip-measured"]) == 0
    out = capsys.readouterr().out
    assert "1 configs timed" in out
    rows3 = read_csv(extended_csv_path(tmp_path))
    assert len(rows3) == 2
    assert sorted(r["n_rows"] for r in rows3) == [16, 32]


def test_sweep_cli_skip_measured_distinguishes_label_and_dtype(
    devices, tmp_path, monkeypatch, capsys
):
    """The skip key includes the strategy label as written (suffix and
    all) and the dtype: a measured plain row must not suppress a
    suffixed-kernel or different-dtype run of the same shape."""
    monkeypatch.setenv("MATVEC_DATA_DIR", str(tmp_path))
    base = ["--strategy", "rowwise", "--devices", "2", "--sizes", "16",
            "--n-reps", "2", "--measure", "sync"]
    assert sweep_main(base + ["--dtype", "float64"]) == 0
    # Same shape, different dtype: runs.
    assert sweep_main(base + ["--dtype", "float32", "--skip-measured"]) == 0
    assert "1 configs timed" in capsys.readouterr().out
    # Same shape/dtype under a label suffix: runs (separate CSV identity).
    assert sweep_main(
        base + ["--dtype", "float64", "--label-suffix", "alt",
                "--skip-measured"]
    ) == 0
    assert "1 configs timed" in capsys.readouterr().out
    # And now all three identities are present exactly once.
    rows = read_csv(extended_csv_path(tmp_path))
    assert sorted((r["strategy"], r["dtype"]) for r in rows) == [
        ("rowwise", "float32"), ("rowwise", "float64"),
        ("rowwise_alt", "float64"),
    ]


def test_sweep_cli_skip_measured_guards():
    """--skip-measured with auto measure (ambiguous row matching) or
    --no-csv (would re-skip forever) is a usage error."""
    with pytest.raises(SystemExit):
        sweep_main(["--strategy", "rowwise", "--sizes", "16",
                    "--skip-measured"])
    with pytest.raises(SystemExit):
        sweep_main(["--strategy", "rowwise", "--sizes", "16",
                    "--measure", "sync", "--no-csv", "--skip-measured"])


def test_sweep_cli_label_suffix(devices, tmp_path, monkeypatch):
    """Kernel-variant rows land under a suffixed strategy name so they never
    blend into the plain per-strategy SpeedUp/Efficiency averaging."""
    monkeypatch.setenv("MATVEC_DATA_DIR", str(tmp_path))
    rc = sweep_main([
        "--strategy", "rowwise", "--devices", "2", "--sizes", "16",
        "--n-reps", "2", "--dtype", "float64", "--label-suffix", "variant",
    ])
    assert rc == 0
    rows = read_csv(csv_path("rowwise_variant", tmp_path))
    assert rows[0]["n_rows"] == 16
    assert not csv_path("rowwise", tmp_path).exists()


def test_sweep_cli_skips_indivisible(devices, tmp_path, capsys):
    rc = sweep_main([
        "--strategy", "rowwise", "--devices", "8", "--sizes", "12",
        "--n-reps", "1", "--no-csv",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "skip rowwise 12x12" in out


def test_sweep_cli_unknown_strategy():
    with pytest.raises(SystemExit, match="unknown matvec strategy"):
        sweep_main(["--strategy", "nope", "--no-csv"])


def test_parser_defaults():
    args = build_parser().parse_args([])
    assert args.mode == "amortized"
    assert args.n_reps == 100
    assert args.sweep == "square"


def test_sweep_rejects_chain_measure_for_reference_mode():
    # The ConfigError from time_matvec would otherwise only surface deep in
    # the sweep loop, after earlier configs already ran.
    with pytest.raises(SystemExit, match="cannot time"):
        sweep_main(["--mode", "both", "--measure", "chain", "--no-csv"])


def test_configure_platform_replaces_inherited_device_count(monkeypatch):
    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform

    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=4 --other"
    )
    configure_platform(None, 8)
    import os

    assert os.environ["XLA_FLAGS"] == (
        "--xla_force_host_platform_device_count=8 --other"
    )


def test_configure_platform_appends_when_absent(monkeypatch):
    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform

    monkeypatch.delenv("XLA_FLAGS", raising=False)
    configure_platform(None, 8)
    import os

    assert (
        os.environ["XLA_FLAGS"] == "--xla_force_host_platform_device_count=8"
    )


def test_sweep_cli_profile_dir(devices, tmp_path):
    rc = sweep_main([
        "--strategy", "rowwise", "--devices", "2", "--sizes", "16",
        "--n-reps", "1", "--no-csv", "--profile-dir", str(tmp_path / "trace"),
    ])
    assert rc == 0
    # jax.profiler writes a plugins/profile/<ts>/ tree with trace artifacts.
    assert any((tmp_path / "trace").rglob("*"))


def test_dispatch_overhead_subtracts_one_rep():
    """The jitter-target base must be dispatch+fence alone: a k=1 run
    includes one kernel execution, and for kernels whose rep time rivals
    the overhead the old t(k=1) estimate tripled measurement wall-time
    (round-3 advisor finding)."""
    from matvec_mpi_multiplier_tpu.bench.timing import _dispatch_overhead

    # Deterministic linear cost model: t(k) = dispatch + rep * k.
    pure, t_k1 = _dispatch_overhead(lambda k: 0.070 + 0.010 * k)
    assert pure == pytest.approx(0.070)
    assert t_k1 == pytest.approx(0.080)
    # Rep time dominating dispatch: estimate stays the dispatch, not 0.5+.
    pure, _ = _dispatch_overhead(lambda k: 0.002 + 0.5 * k)
    assert pure == pytest.approx(0.002)
    # Degenerate noise (k=2 cheaper than k=1, or negative differences)
    # clamps instead of going negative; t_k1 keeps the conservative value
    # callers floor the jitter target at, so a correlated burst across the
    # k=2 runs (pure collapses to ~0) can never collapse the target below
    # the old dispatch+one-rep scale.
    pure, t_k1 = _dispatch_overhead(lambda k: 0.1 - 0.03 * k)
    assert pure >= 0.0
    assert t_k1 == pytest.approx(0.07)
    pure, t_k1 = _dispatch_overhead(
        lambda k: 0.070 if k == 1 else 0.150  # burst spans both k=2 runs
    )
    assert pure == 0.0
    assert t_k1 == pytest.approx(0.070)

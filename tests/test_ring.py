"""Ring collective tests: the explicit neighbor-ring reduce-scatter and
all-gather must agree exactly with the XLA collectives they reimplement, and
the colwise_ring strategy must match the numpy oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from matvec_mpi_multiplier_tpu import get_strategy, make_mesh
from matvec_mpi_multiplier_tpu.parallel.mesh import make_1d_mesh
from matvec_mpi_multiplier_tpu.parallel.ring import (
    ring_all_gather,
    ring_psum_scatter,
)
from matvec_mpi_multiplier_tpu.utils.compat import shard_map


def _shard_map_1d(body, mesh, in_spec, out_spec, check_vma=True):
    return jax.jit(
        shard_map(body, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec,
                      check_vma=check_vma)
    )


@pytest.mark.parametrize("p", [2, 4, 8])
def test_ring_psum_scatter_matches_lax(devices, rng, p):
    mesh = make_1d_mesh(p, axis_name="r")
    # Each device holds a full-length partial: input sharded on a leading
    # device axis of size p, i.e. shape (p, n) with spec P('r').
    n = 16 * p
    partials = rng.standard_normal((p, n))

    ours = _shard_map_1d(
        lambda x: ring_psum_scatter(x[0], "r"), mesh, P("r"), P("r")
    )(jnp.asarray(partials))
    theirs = _shard_map_1d(
        lambda x: jax.lax.psum_scatter(x[0], "r", tiled=True),
        mesh, P("r"), P("r"),
    )(jnp.asarray(partials))
    np.testing.assert_allclose(np.asarray(ours), np.asarray(theirs), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(ours), partials.sum(0), rtol=1e-12)


@pytest.mark.parametrize("p", [2, 4, 8])
def test_ring_all_gather_matches_lax(devices, rng, p):
    mesh = make_1d_mesh(p, axis_name="r")
    chunks = rng.standard_normal((p * 8,))

    # check_vma=False: the gathered value is replicated but the vma system
    # can't prove it through ppermute (see ring_all_gather docstring).
    ours = _shard_map_1d(
        lambda x: ring_all_gather(x, "r"), mesh, P("r"), P(), check_vma=False
    )(jnp.asarray(chunks))
    np.testing.assert_allclose(np.asarray(ours), chunks, rtol=1e-15)


def test_ring_psum_scatter_p1(devices):
    mesh = make_1d_mesh(1, axis_name="r")
    x = jnp.arange(8.0)
    out = _shard_map_1d(lambda v: ring_psum_scatter(v, "r"), mesh, P(), P())(x)
    np.testing.assert_array_equal(np.asarray(out), np.arange(8.0))


def test_ring_over_2d_mesh_flat_axes(devices, rng):
    """The colwise_ring strategy rings over BOTH axes of a 2-D mesh as one
    logical flat axis (the reference's flat-communicator view)."""
    a = rng.standard_normal((16, 32))
    x = rng.standard_normal(32)
    mesh = make_mesh(8)  # 2x4
    strat = get_strategy("colwise_ring")
    strat.validate(16, 32, mesh)
    y = np.asarray(strat.build(mesh)(jnp.asarray(a), jnp.asarray(x)))
    np.testing.assert_allclose(y, a @ x, rtol=1e-10)


@pytest.mark.parametrize("name", ["colwise_ring", "colwise_ring_overlap"])
@pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
def test_colwise_ring_strategy_oracle(devices, rng, n_dev, name):
    a = rng.standard_normal((16, 16))
    x = rng.standard_normal(16)
    mesh = make_mesh(n_dev)
    strat = get_strategy(name)
    y = np.asarray(strat.build(mesh)(jnp.asarray(a), jnp.asarray(x)))
    np.testing.assert_allclose(y, a @ x, rtol=1e-10)


@pytest.mark.parametrize("name", ["colwise_ring", "colwise_ring_overlap"])
def test_colwise_ring_sharded_output(devices, rng, name):
    a = rng.standard_normal((16, 16))
    x = rng.standard_normal(16)
    mesh = make_mesh(8)
    y = get_strategy(name).build(mesh, gather_output=False)(
        jnp.asarray(a), jnp.asarray(x)
    )
    assert y.sharding.spec == P(("rows", "cols"))
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-10)


def test_ring_matvec_matches_psum_scatter(devices):
    import jax
    from jax.sharding import PartitionSpec as P

    from matvec_mpi_multiplier_tpu.ops.gemv import gemv_xla
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_1d_mesh
    from matvec_mpi_multiplier_tpu.parallel.ring import ring_matvec

    mesh = make_1d_mesh(8, axis_name="d")
    rng = np.random.default_rng(7)
    m, k = 64, 128
    a = rng.uniform(0, 10, (m, k))
    x = rng.uniform(0, 10, k)

    def overlapped(a, x):
        return ring_matvec(a, x, "d", gemv_xla)

    def reference(a, x):
        y = gemv_xla(a, x)
        return jax.lax.psum_scatter(y, "d", tiled=True)

    run_o = jax.jit(
        shard_map(
            overlapped, mesh=mesh, in_specs=(P(None, "d"), P("d")),
            out_specs=P("d"),
        )
    )
    run_r = jax.jit(
        shard_map(
            reference, mesh=mesh, in_specs=(P(None, "d"), P("d")),
            out_specs=P("d"),
        )
    )
    np.testing.assert_allclose(
        np.asarray(run_o(a, x)), np.asarray(run_r(a, x)), rtol=1e-12
    )
    np.testing.assert_allclose(np.asarray(run_o(a, x)), a @ x, rtol=1e-12)


def test_ring_matvec_rejects_indivisible_rows(devices):
    import jax
    from jax.sharding import PartitionSpec as P

    from matvec_mpi_multiplier_tpu.ops.gemv import gemv_xla
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_1d_mesh
    from matvec_mpi_multiplier_tpu.parallel.ring import ring_matvec

    mesh = make_1d_mesh(8, axis_name="d")
    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(
            shard_map(
                lambda a, x: ring_matvec(a, x, "d", gemv_xla),
                mesh=mesh, in_specs=(P(None, "d"), P("d")), out_specs=P("d"),
            )
        )(np.ones((12, 16)), np.ones(16))


@pytest.mark.parametrize(
    "kernel",
    ["xla", "xla_colwise", "pallas", "compensated", "ozaki", "ozaki6",
     "ozaki_i8"],
)
def test_colwise_ring_overlap_kernel_matrix(devices, rng, kernel):
    # ring_matvec hands each registered kernel small (m/p, k/p) dynamic-sliced
    # tiles rather than the full panel — every kernel tier must survive that.
    a = rng.standard_normal((16, 32))
    x = rng.standard_normal(32)
    mesh = make_mesh(8)
    y = get_strategy("colwise_ring_overlap").build(mesh, kernel=kernel)(
        jnp.asarray(a), jnp.asarray(x)
    )
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-6)


@pytest.mark.parametrize("name", ["colwise_ring", "colwise_ring_overlap"])
@pytest.mark.parametrize("dtype,rtol", [("float32", 1e-5), ("bfloat16", 0.03)])
def test_ring_strategies_reduced_precision(devices, rng, name, dtype, rtol):
    a = rng.standard_normal((16, 32)).astype(np.float32)
    x = rng.standard_normal(32).astype(np.float32)
    mesh = make_mesh(8)
    y = get_strategy(name).build(mesh)(
        jnp.asarray(a, dtype), jnp.asarray(x, dtype)
    )
    np.testing.assert_allclose(
        np.asarray(y, dtype=np.float32), a @ x, rtol=rtol, atol=rtol
    )


@pytest.mark.parametrize("name", ["colwise_ring", "colwise_ring_overlap"])
@pytest.mark.parametrize("n_dev", [1, 2, 4])
def test_ring_strategies_fixture(devices, fixture_4x8, name, n_dev):
    # The committed 4x8 fixture (4 rows -> at most 4 ring chunks).
    from tests.test_strategies import FIXTURE_PRODUCT

    a, x = fixture_4x8
    mesh = make_mesh(n_dev)
    strat = get_strategy(name)
    strat.validate(a.shape[0], a.shape[1], mesh)
    y = np.asarray(strat.build(mesh)(jnp.asarray(a), jnp.asarray(x)))
    np.testing.assert_allclose(y, FIXTURE_PRODUCT, rtol=1e-12)


@pytest.mark.parametrize(
    "name", ["rowwise", "blockwise", "colwise_ring", "colwise_a2a"]
)
@pytest.mark.parametrize("n_dev", [1, 2, 8])
def test_ring_gather_output_through_build(devices, rng, n_dev, name):
    """gather_output="ring" must produce the same fully-replicated y as the
    default gather, via ring_all_gather — the MPI_Gather analog
    (src/multiplier_rowwise.c:141) as explicit neighbor traffic, reachable
    from every sharded-output strategy (not just its unit test)."""
    a = rng.standard_normal((16, 16))
    x = rng.standard_normal(16)
    mesh = make_mesh(n_dev)
    strat = get_strategy(name)
    y = strat.build(mesh, gather_output="ring")(jnp.asarray(a), jnp.asarray(x))
    # Replicated in sharding, not just in value.
    assert y.sharding.is_fully_replicated
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-10)


def test_ring_gather_output_replicated_native_is_plain_gather(devices, rng):
    """Plain colwise's native y is already replicated (P()) — 'ring' has
    nothing to gather and must behave exactly like gather_output=True."""
    a = rng.standard_normal((16, 16))
    x = rng.standard_normal(16)
    mesh = make_mesh(8)
    y = get_strategy("colwise").build(mesh, gather_output="ring")(
        jnp.asarray(a), jnp.asarray(x)
    )
    assert y.sharding.is_fully_replicated
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-10)


@pytest.mark.parametrize("kernel", ["ozaki", "ozaki_i8"])
def test_colwise_ring_overlap_ozaki_fp32_slicing(devices, rng, kernel):
    """fp32 operands force the ozaki kernels' actual slicing path (fp64
    inputs delegate to the plain fp64 dot) inside ring_matvec's dynamic
    tile slices — frexp/round/int casts must all trace under shard_map."""
    a = rng.standard_normal((16, 32)).astype(np.float32)
    x = rng.standard_normal(32).astype(np.float32)
    mesh = make_mesh(8)
    y = get_strategy("colwise_ring_overlap").build(mesh, kernel=kernel)(
        jnp.asarray(a), jnp.asarray(x)
    )
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-5)

"""Autotuner tests: cache round-trip, fingerprint gating, and the ``auto``
dispatch tiers' fall-back and cache-hit behavior.

The measurement layer itself (tuning/search.py) is exercised with a faked
timer — the selection/recording logic is what needs pinning; real slope
measurement is bench/timing.py's own, already-tested machinery.
"""

import json

import numpy as np
import pytest

from matvec_mpi_multiplier_tpu import get_strategy, make_mesh
from matvec_mpi_multiplier_tpu.tuning import (
    TuningCache,
    combine_key,
    gemv_key,
    lookup_combine,
    lookup_gemv,
    platform_fingerprint,
    reset_cache,
)
from matvec_mpi_multiplier_tpu.tuning.cache import CACHE_VERSION


@pytest.fixture()
def cache_path(tmp_path, monkeypatch):
    """Redirect the cache (dispatch singleton included) to a temp file."""
    path = tmp_path / "tuning_cache.json"
    monkeypatch.setenv("MATVEC_TUNING_CACHE", str(path))
    reset_cache()
    yield path
    reset_cache()


@pytest.fixture()
def operands(rng):
    a = rng.uniform(0, 10, (64, 64)).astype(np.float32)
    x = rng.uniform(0, 10, (64,)).astype(np.float32)
    return a, x


# ------------------------------------------------------------------ cache


def test_cache_round_trip(cache_path):
    cache = TuningCache.load(cache_path)
    key = gemv_key(512, 4096, "float32")
    decision = {"kernel": "pallas", "bm": 512, "bk": 2048, "time_s": 1e-4}
    cache.record(key, decision)
    assert cache.save() == cache_path

    reloaded = TuningCache.load(cache_path)
    assert reloaded.lookup(key) == decision
    assert len(reloaded) == 1
    # The file is the documented versioned schema.
    raw = json.loads(cache_path.read_text())
    assert raw["version"] == CACHE_VERSION
    assert key in raw["entries"]


def test_fingerprint_mismatch_is_a_miss(cache_path):
    """A decision tuned on another platform/JAX must never be served: its
    fingerprint is baked into the key, so the lookup misses and dispatch
    falls back to the static default (re-tune territory)."""
    cache = TuningCache.load(cache_path)
    foreign = gemv_key(64, 64, "float32", fingerprint="tpu:v5e:jax-9.9.9")
    cache.record(foreign, {"kernel": "pallas", "bm": 8, "bk": 128})
    cache.save()
    reset_cache()

    assert "tpu:v5e" not in platform_fingerprint()
    assert lookup_gemv(64, 64, "float32") is None
    # The foreign entry itself survives the round-trip untouched.
    assert TuningCache.load(cache_path).lookup(foreign) is not None


def test_wrong_version_file_loads_empty(cache_path):
    cache_path.write_text(json.dumps({
        "version": CACHE_VERSION + 1,
        "entries": {gemv_key(8, 8, "float32"): {"kernel": "xla"}},
    }))
    assert len(TuningCache.load(cache_path)) == 0


def test_corrupt_file_loads_empty(cache_path):
    cache_path.write_text("{ this is not json")
    assert len(TuningCache.load(cache_path)) == 0


def test_save_is_atomic_overwrite(cache_path):
    c1 = TuningCache.load(cache_path)
    c1.record(gemv_key(8, 8, "float32"), {"kernel": "xla"})
    c1.save()
    c2 = TuningCache.load(cache_path)
    c2.record(gemv_key(16, 16, "float32"), {"kernel": "xla"})
    c2.save()
    assert len(TuningCache.load(cache_path)) == 2


# ------------------------------------------------------- kernel="auto"


def test_kernel_auto_cold_cache_matches_xla(devices, cache_path, operands):
    """On a cold cache the auto tier must be exactly the static default."""
    a, x = operands
    mesh = make_mesh(8)
    strat = get_strategy("rowwise")
    y_auto = strat.build(mesh, kernel="auto")(a, x)
    y_xla = strat.build(mesh, kernel="xla")(a, x)
    np.testing.assert_array_equal(np.asarray(y_auto), np.asarray(y_xla))


def test_kernel_auto_dispatches_cached_winner(
    devices, cache_path, operands, monkeypatch
):
    """A recorded pallas winner for the LOCAL shape must actually route
    dispatch through the pallas tier (and still be correct)."""
    import matvec_mpi_multiplier_tpu.ops.pallas_gemv as pg

    a, x = operands
    mesh = make_mesh(8)
    # rowwise on p=8: local blocks are (8, 64).
    cache = TuningCache.load(cache_path)
    cache.record(
        gemv_key(8, 64, "float32"),
        {"kernel": "pallas", "bm": 8, "bk": 128},
    )
    cache.save()
    reset_cache()

    calls = []
    real = pg.gemv_pallas

    def spy(a_, x_, **kw):
        calls.append(kw)
        return real(a_, x_, **kw)

    monkeypatch.setattr(pg, "gemv_pallas", spy)
    y = get_strategy("rowwise").build(mesh, kernel="auto")(a, x)
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-5)
    assert calls and calls[0] == {"bm": 8, "bk": 128}


def test_kernel_auto_unregistered_winner_falls_back(
    devices, cache_path, operands
):
    """A cached winner whose tier isn't registered here (e.g. 'native'
    tuned where the .so existed) must fall back to XLA, not crash."""
    a, x = operands
    mesh = make_mesh(8)
    cache = TuningCache.load(cache_path)
    cache.record(gemv_key(8, 64, "float32"), {"kernel": "no_such_tier"})
    cache.save()
    reset_cache()
    y = get_strategy("rowwise").build(mesh, kernel="auto")(a, x)
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-5)


# ------------------------------------------------------ combine="auto"


def test_combine_auto_cold_cache_matches_default(
    devices, cache_path, operands
):
    a, x = operands
    mesh = make_mesh(8)
    strat = get_strategy("colwise")
    y_auto = strat.build(mesh, combine="auto")(a, x)
    y_def = strat.build(mesh)(a, x)
    np.testing.assert_array_equal(np.asarray(y_auto), np.asarray(y_def))


def test_combine_auto_dispatches_cached_winner(
    devices, cache_path, operands, monkeypatch
):
    import matvec_mpi_multiplier_tpu.parallel.ring as ring

    a, x = operands
    mesh = make_mesh(8)
    cache = TuningCache.load(cache_path)
    cache.record(
        combine_key("matvec", "colwise", 64, 64, 8, "float32"),
        {"combine": "ring"},
    )
    cache.save()
    reset_cache()
    assert lookup_combine(
        op="matvec", strategy="colwise", m=64, k=64, p=8, dtype="float32"
    ) == "ring"

    calls = []
    real = ring.ring_psum_scatter

    def spy(v, axes):
        calls.append(axes)
        return real(v, axes)

    monkeypatch.setattr(ring, "ring_psum_scatter", spy)
    y = get_strategy("colwise").build(mesh, combine="auto")(a, x)
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-4)
    assert calls, "cached 'ring' winner did not route through the ring"


def test_combine_auto_invalid_winner_falls_back(
    devices, cache_path, rng
):
    """A cached scatter-family winner for a shape whose rows don't divide
    the mesh must fall back to the strategy default, not crash: the bound
    candidate list is filtered against combine_candidates, and the default
    (psum for plain colwise) is always valid where validate() passes."""
    m, k = 60, 64  # 60 % 8 != 0: scatter family invalid, psum fine
    a = rng.uniform(0, 10, (m, k)).astype(np.float32)
    x = rng.uniform(0, 10, (k,)).astype(np.float32)
    mesh = make_mesh(8)
    cache = TuningCache.load(cache_path)
    cache.record(
        combine_key("matvec", "colwise", m, k, 8, "float32"),
        {"combine": "definitely_not_a_schedule"},
    )
    cache.save()
    reset_cache()
    y = get_strategy("colwise").build(mesh, combine="auto")(a, x)
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-4)


def test_combine_constructor_auto(devices, cache_path, operands):
    """get_strategy('colwise', combine='auto') defers like build(combine=)."""
    a, x = operands
    mesh = make_mesh(8)
    y = get_strategy("colwise", combine="auto").build(mesh)(a, x)
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-4)


def test_combine_never_overrides_ungathered_output(devices, cache_path, rng):
    """gather_output=False is a sharding contract: a gather-schedule combine
    (explicit 'ring' or a cache-chosen one) must not replicate the output
    the caller asked to keep sharded."""
    from jax.sharding import PartitionSpec as P

    a = rng.uniform(0, 10, (64, 64)).astype(np.float32)
    x = rng.uniform(0, 10, (64,)).astype(np.float32)
    mesh = make_mesh(8)
    cache = TuningCache.load(cache_path)
    cache.record(
        combine_key("matvec", "rowwise", 64, 64, 8, "float32"),
        {"combine": "ring"},
    )
    cache.save()
    reset_cache()
    for comb in ("ring", "auto"):
        y = get_strategy("rowwise").build(
            mesh, gather_output=False, combine=comb
        )(a, x)
        assert y.sharding.spec != P(), comb


def test_supports_combine_predicate(devices):
    assert get_strategy("rowwise").supports_combine("ring")
    assert get_strategy("rowwise").supports_combine("auto")
    assert not get_strategy("rowwise").supports_combine("psum_scatter")
    assert get_strategy("colwise").supports_combine("a2a")
    assert not get_strategy("colwise").supports_combine("gather")


def test_combine_rejects_unknown_schedule(devices):
    with pytest.raises(ValueError, match="combine"):
        get_strategy("colwise", combine="nope")
    mesh = make_mesh(8)
    with pytest.raises(ValueError, match="combine schedule"):
        get_strategy("rowwise").build(mesh, combine="a2a")


# ------------------------------------------------------------- search


def test_tune_gemv_records_fastest_candidate(cache_path, monkeypatch):
    from matvec_mpi_multiplier_tpu.tuning import search

    # Off-TPU the pallas ladder is gated out of the candidate list (interpret
    # mode); force it in so the tile axis is part of what's being ranked.
    monkeypatch.setenv("MATVEC_TUNE_PALLAS", "1")
    cands = search.gemv_candidates(32, 128, "float32")
    fast = search._candidate_label(cands[1])  # make the SECOND fastest

    real_fn = search._candidate_gemv_fn

    def tagged(cand):
        fn = real_fn(cand)

        def wrapper(*a, **kw):
            return fn(*a, **kw)

        wrapper.label = search._candidate_label(cand)
        return wrapper

    def fake_measure(fn, args, *, n_reps, samples, measure="loop"):
        label = getattr(fn, "label", None)
        if label is None:
            return 99.0  # the discarded cold-process warmup probe
        return 1.0 if label == fast else 10.0

    monkeypatch.setattr(search, "_candidate_gemv_fn", tagged)
    monkeypatch.setattr(search, "_measure_fn", fake_measure)
    cache = TuningCache.load(cache_path)
    decision = search.tune_gemv(
        32, 128, "float32", cache, log=lambda *_: None
    )
    assert decision is not None
    for key, val in cands[1].items():
        assert decision[key] == val
    assert decision["time_s"] == 1.0
    # Recorded under the right key, re-served without re-measuring.
    assert cache.lookup(gemv_key(32, 128, "float32")) == decision
    monkeypatch.setattr(
        search, "_measure_fn",
        lambda *a, **k: pytest.fail("cache hit must not re-measure"),
    )
    again = search.tune_gemv(32, 128, "float32", cache, log=lambda *_: None)
    assert again == decision


def test_pick_winner_hysteresis():
    from matvec_mpi_multiplier_tpu.tuning.search import _pick_winner

    # Within the margin the static default keeps the seat (noise guard)...
    assert _pick_winner({"psum": 10.0, "ring": 9.8}, default="psum") == "psum"
    # ...a real gain displaces it...
    assert _pick_winner({"psum": 10.0, "ring": 9.0}, default="psum") == "ring"
    # ...and an unmeasurable default can't block the only measured option.
    assert _pick_winner({"ring": 5.0}, default="psum") == "ring"
    assert _pick_winner({}, default="psum") is None


def test_gemv_candidates_cover_ladder_and_tiers(monkeypatch):
    monkeypatch.setenv("MATVEC_TUNE_PALLAS", "1")
    from matvec_mpi_multiplier_tpu.ops.pallas_gemv import (
        TILE_BYTE_BUDGET,
        default_tiles,
        tile_ladder,
    )
    from matvec_mpi_multiplier_tpu.tuning.search import gemv_candidates

    cands = gemv_candidates(512, 4096, "float32")
    assert cands[0] == {"kernel": "xla"}
    pallas = [c for c in cands if c["kernel"] == "pallas"]
    assert pallas, "pallas ladder missing"
    ladder = tile_ladder(512, 4096, 4)
    assert [(c["bm"], c["bk"]) for c in pallas] == ladder
    # Ladder discipline: aligned divisors inside the byte budget, static
    # default first.
    assert ladder[0] == default_tiles(512, 4096, 4)
    for bm, bk in ladder:
        assert 512 % bm == 0 and 4096 % bk == 0
        assert bm % 16 == 0 and bk % 128 == 0
        assert bm * bk * 4 <= TILE_BYTE_BUDGET


def test_local_gemv_shapes(devices):
    from matvec_mpi_multiplier_tpu.tuning.search import local_gemv_shapes

    mesh = make_mesh(8)
    assert local_gemv_shapes("rowwise", 64, 48, mesh) == {(8, 48)}
    assert local_gemv_shapes("colwise", 64, 48, mesh) == {(64, 6), (8, 6)}
    assert local_gemv_shapes("rowwise", 60, 48, mesh) == set()


def test_gemm_candidates_cover_tile_ladder(monkeypatch):
    monkeypatch.setenv("MATVEC_TUNE_PALLAS", "1")
    from matvec_mpi_multiplier_tpu.ops.pallas_gemm import (
        TILE_BYTE_BUDGET,
        default_gemm_tiles,
        gemm_tile_ladder,
    )
    from matvec_mpi_multiplier_tpu.tuning.search import gemm_candidates

    m, k, n = 512, 4096, 256
    cands = gemm_candidates(m, k, n, "float32")
    assert cands[0] == {"kernel": "xla"}
    pallas = [c for c in cands if c["kernel"] == "pallas"]
    assert pallas, "pallas tile ladder missing"
    ladder = gemm_tile_ladder(m, n, k, 4)
    assert [(c["bm"], c["bn"], c["bk"]) for c in pallas] == ladder
    # Ladder discipline: aligned divisors inside the byte budget, static
    # default first (the GEMM face of the gemv ladder invariants).
    assert ladder[0] == default_gemm_tiles(m, n, k, 4)
    for bm, bn, bk in ladder:
        assert m % bm == 0 and n % bn == 0 and k % bk == 0
        assert bm % 16 == 0 and bn % 128 == 0 and bk % 128 == 0
        assert max(bm, bn) * bk * 4 <= TILE_BYTE_BUDGET


def test_tune_gemm_records_tile_winner(cache_path, monkeypatch):
    """The GEMM tuner ranks the tile ladder like the gemv one: a winning
    pallas candidate is recorded WITH its (bm, bn, bk), and the auto tier
    re-serves it without re-measuring."""
    from matvec_mpi_multiplier_tpu.tuning import gemm_key, search

    monkeypatch.setenv("MATVEC_TUNE_PALLAS", "1")
    m, k, n = 64, 256, 128
    cands = search.gemm_candidates(m, k, n, "float32")
    assert any(c["kernel"] == "pallas" for c in cands)
    fast = search._gemm_candidate_label(cands[1])  # a pallas tile entry

    real_fn = search._candidate_gemm_fn

    def tagged(cand):
        fn = real_fn(cand)

        def wrapper(*a, **kw):
            return fn(*a, **kw)

        wrapper.label = search._gemm_candidate_label(cand)
        return wrapper

    def fake_measure(fn, args, *, n_reps, samples, measure="loop"):
        label = getattr(fn, "label", None)
        if label is None:
            return 99.0  # the discarded cold-process warmup probe
        return 1.0 if label == fast else 10.0

    monkeypatch.setattr(search, "_candidate_gemm_fn", tagged)
    monkeypatch.setattr(search, "_measure_fn", fake_measure)
    cache = TuningCache.load(cache_path)
    decision = search.tune_gemm(m, k, n, "float32", cache, log=lambda *_: None)
    assert decision is not None
    for key, val in cands[1].items():
        assert decision[key] == val
    assert cache.lookup(gemm_key(m, k, n, "float32")) == decision
    monkeypatch.setattr(
        search, "_measure_fn",
        lambda *a, **k: pytest.fail("cache hit must not re-measure"),
    )
    again = search.tune_gemm(m, k, n, "float32", cache, log=lambda *_: None)
    assert again == decision


def test_gemm_auto_kernel_dispatches_tiled_winner(
    devices, cache_path, rng, monkeypatch
):
    """A recorded pallas GEMM winner routes matmul_auto through the pinned
    (bm, bn, bk) tile kernel."""
    import matvec_mpi_multiplier_tpu.ops.pallas_gemm as pg
    from matvec_mpi_multiplier_tpu.tuning import gemm_key

    a = rng.uniform(0, 10, (32, 128)).astype(np.float32)
    b = rng.uniform(0, 10, (128, 128)).astype(np.float32)
    cache = TuningCache.load(cache_path)
    cache.record(
        gemm_key(32, 128, 128, "float32"),
        {"kernel": "pallas", "bm": 32, "bn": 128, "bk": 128},
    )
    cache.save()
    reset_cache()

    calls = []
    real = pg.matmul_pallas

    def spy(a_, b_, **kw):
        calls.append(kw)
        return real(a_, b_, **kw)

    monkeypatch.setattr(pg, "matmul_pallas", spy)
    from matvec_mpi_multiplier_tpu.ops.gemm_kernels import get_gemm_kernel

    c = get_gemm_kernel("auto")(a, b)
    np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-5)
    assert calls and calls[0] == {"bm": 32, "bn": 128, "bk": 128}


def test_tune_combine_smoke(devices, cache_path):
    """One real (tiny) combine tuning pass on the CPU mesh: records a valid
    winner and every measured candidate, and the auto tier then serves it."""
    from matvec_mpi_multiplier_tpu.tuning import search

    mesh = make_mesh(2)
    cache = TuningCache.load(cache_path)
    decision = search.tune_combine(
        "colwise", mesh, 16, 16, "float32", cache,
        measure="sync", n_reps=2, samples=1, log=lambda *_: None,
    )
    assert decision is not None
    assert decision["combine"] in (
        "psum", "psum_scatter", "ring", "ring_overlap", "a2a",
        "overlap", "overlap_ring"
    )
    assert set(decision["candidates"]) <= {
        "psum", "psum_scatter", "ring", "ring_overlap", "a2a",
        "overlap", "overlap_ring"
    }
    cache.save()
    reset_cache()
    assert lookup_combine(
        op="matvec", strategy="colwise", m=16, k=16, p=2, dtype="float32"
    ) == decision["combine"]


# ------------------------------------------------------- gemm combine


def test_build_gemm_accepts_combine_names(devices, rng):
    """Satellite contract: the GEMM builder accepts combine=... like
    MatvecStrategy.build — every in-body schedule produces the same
    product, and the matvec-only names are rejected."""
    from matvec_mpi_multiplier_tpu import build_gemm

    mesh = make_mesh(8)
    a = rng.uniform(0, 10, (64, 64)).astype(np.float32)
    b = rng.uniform(0, 10, (64, 16)).astype(np.float32)
    want = a @ b
    for comb in ("psum", "psum_scatter", "ring", "ring_overlap", "a2a"):
        c = build_gemm("colwise", mesh, combine=comb)(a, b)
        np.testing.assert_allclose(np.asarray(c), want, rtol=1e-4), comb
    with pytest.raises(ValueError, match="combine"):
        build_gemm("colwise", mesh, combine="nope")
    with pytest.raises(ValueError, match="batched combine"):
        build_gemm("rowwise", mesh, combine="ring")(a, b)


def test_build_gemm_combine_auto_dispatches_cached_winner(
    devices, rng, cache_path, monkeypatch
):
    import matvec_mpi_multiplier_tpu.parallel.ring as ring

    mesh = make_mesh(8)
    a = rng.uniform(0, 10, (64, 64)).astype(np.float32)
    b = rng.uniform(0, 10, (64, 8)).astype(np.float32)
    cache = TuningCache.load(cache_path)
    cache.record(
        combine_key("gemm", "colwise", 64, 64, 8, "float32"),
        {"combine": "ring"},
    )
    cache.save()
    reset_cache()

    calls = []
    real = ring.ring_psum_scatter

    def spy(v, axes):
        calls.append(axes)
        return real(v, axes)

    monkeypatch.setattr(ring, "ring_psum_scatter", spy)
    from matvec_mpi_multiplier_tpu import build_gemm

    c = build_gemm("colwise", mesh, combine="auto")(a, b)
    np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-4)
    assert calls, "cached gemm 'ring' winner did not route through the ring"


def test_tune_gemm_combine_smoke(devices, cache_path):
    from matvec_mpi_multiplier_tpu.tuning import search

    mesh = make_mesh(2)
    cache = TuningCache.load(cache_path)
    decision = search.tune_gemm_combine(
        "colwise", mesh, 16, 16, 4, "float32", cache,
        measure="sync", n_reps=2, samples=1, log=lambda *_: None,
    )
    assert decision is not None
    assert decision["combine"] in (
        "psum", "psum_scatter", "ring", "ring_overlap", "a2a",
        "overlap", "overlap_ring"
    )
    cache.save()
    reset_cache()
    assert lookup_combine(
        op="gemm", strategy="colwise", m=16, k=16, p=2, dtype="float32"
    ) == decision["combine"]
    # No in-body combine for rowwise: nothing to tune, no entry recorded.
    assert search.tune_gemm_combine(
        "rowwise", mesh, 16, 16, 4, "float32", cache,
        measure="sync", n_reps=2, samples=1, log=lambda *_: None,
    ) is None


# --------------------------------------------------------- promotion


def test_tune_promotion_smoke(devices, cache_path):
    """One real (tiny) promotion pass: records per-bucket GEMM times and a
    b* consistent with them, and lookup_promotion serves the decision."""
    from matvec_mpi_multiplier_tpu.tuning import lookup_promotion
    from matvec_mpi_multiplier_tpu.tuning.search import tune_promotion

    mesh = make_mesh(2)
    cache = TuningCache.load(cache_path)
    decision = tune_promotion(
        "rowwise", mesh, 64, 64, "float32", cache, buckets=(2, 4),
        n_reps=2, samples=1, log=lambda *_: None,
    )
    assert decision is not None
    assert set(decision) == {"b_star", "seq_time_s", "gemm_times"}
    assert decision["b_star"] in (None, 2, 4)
    assert decision["seq_time_s"] > 0
    assert set(decision["gemm_times"]) <= {"2", "4"}
    cache.save()
    reset_cache()
    assert lookup_promotion(
        strategy="rowwise", m=64, k=64, p=2, dtype="float32"
    ) == decision
    # Invalid shape for the strategy: nothing to tune.
    assert tune_promotion(
        "rowwise", mesh, 63, 64, "float32", cache, buckets=(2,),
        n_reps=2, samples=1, log=lambda *_: None,
    ) is None


# ------------------------------------------------ solver iteration tier


def test_tune_solver_kernel_smoke(devices, cache_path, monkeypatch):
    """One real (tiny, interpret-gated) solver-tier race: both tiers run
    the SAME fixed-iteration solve (rtol=0 pins SOLVER_RACE_ITERS
    while-body trips), the winner and both candidates' per-iteration
    times are recorded, and lookup_solver_kernel serves the decision —
    which the engine's solver_kernel="auto" then consumes."""
    from matvec_mpi_multiplier_tpu.engine import MatvecEngine
    from matvec_mpi_multiplier_tpu.tuning import lookup_solver_kernel
    from matvec_mpi_multiplier_tpu.tuning.search import (
        SOLVER_RACE_ITERS,
        tune_solver_kernel,
    )

    # Off-TPU the fused candidate runs in interpret mode — never a fair
    # race, so it is gated out of tuning unless explicitly opted in.
    monkeypatch.setenv("MATVEC_TUNE_PALLAS", "1")
    mesh = make_mesh(8)
    cache = TuningCache.load(cache_path)
    decision = tune_solver_kernel(
        "cg", "rowwise", mesh, 64, 64, "float32", cache,
        n_reps=2, samples=1, measure="sync", log=lambda *_: None,
    )
    assert decision is not None
    assert decision["solver_kernel"] in ("xla", "pallas_fused")
    assert set(decision["candidates"]) == {"xla", "pallas_fused"}
    assert decision["race_iters"] == SOLVER_RACE_ITERS
    assert decision["iter_s"] == pytest.approx(
        decision["time_s"] / SOLVER_RACE_ITERS
    )
    cache.save()
    reset_cache()
    assert lookup_solver_kernel(
        op="cg", strategy="rowwise", m=64, k=64, p=8, dtype="float32",
        storage="native",
    ) == decision
    a = np.random.default_rng(0).standard_normal((64, 64)).astype("float32")
    a = a @ a.T + 64 * np.eye(64, dtype="float32")
    engine = MatvecEngine(a, mesh, strategy="rowwise", promote=None,
                          solver_kernel="auto")
    assert engine._resolve_solver_kernel_locked("cg") == decision["solver_kernel"]
    # auto never routes a basis-building op at the fused tier.
    assert engine._resolve_solver_kernel_locked("gmres") == "xla"


def test_tune_solver_kernel_skips_untunable_cells(devices, cache_path):
    """No silent work on cells the fused tier cannot serve: non-square
    shapes, basis-building ops, and 2-D-sharded strategies return None
    without racing anything."""
    from matvec_mpi_multiplier_tpu.tuning.search import tune_solver_kernel

    mesh = make_mesh(8)
    cache = TuningCache.load(cache_path)
    kw = dict(n_reps=2, samples=1, log=lambda *_: None)
    assert tune_solver_kernel(
        "cg", "rowwise", mesh, 64, 128, "float32", cache, **kw
    ) is None
    assert tune_solver_kernel(
        "gmres", "rowwise", mesh, 64, 64, "float32", cache, **kw
    ) is None
    assert tune_solver_kernel(
        "cg", "blockwise", mesh, 64, 64, "float32", cache, **kw
    ) is None
    assert len(cache) == 0


# ------------------------------------------------- multi-host broadcast


@pytest.mark.parametrize("version", [4, 5])
def test_prior_schema_files_still_load(cache_path, version):
    """v6 bump compatibility: v4/v5 files (pre-solver-kernel entries)
    keep serving their decisions instead of forcing a silent re-tune."""
    key = gemv_key(8, 8, "float32")
    cache_path.write_text(json.dumps({
        "version": version, "entries": {key: {"kernel": "xla"}},
    }))
    assert TuningCache.load(cache_path).lookup(key) == {"kernel": "xla"}


def test_cache_v1_file_still_loads(cache_path):
    """Schema bump compatibility: a version-1 file (pre-promote entries)
    keeps serving its decisions instead of forcing a silent re-tune."""
    key = gemv_key(8, 8, "float32")
    cache_path.write_text(json.dumps({
        "version": 1, "entries": {key: {"kernel": "xla"}},
    }))
    assert TuningCache.load(cache_path).lookup(key) == {"kernel": "xla"}


def test_broadcast_decisions_single_process_is_noop(cache_path):
    from matvec_mpi_multiplier_tpu.tuning import broadcast_decisions

    cache = TuningCache.load(cache_path)
    cache.record(gemv_key(8, 8, "float32"), {"kernel": "xla"})
    assert broadcast_decisions(cache) is cache
    assert len(cache) == 1


def test_broadcast_decisions_from_coordinator(cache_path, monkeypatch):
    """Multi-host: non-coordinator processes must end up with the
    coordinator's entries without ever reading the file — exercised with a
    faked 2-process runtime (the broadcast itself is replayed from what
    the coordinator side sent)."""
    import jax
    from jax.experimental import multihost_utils

    from matvec_mpi_multiplier_tpu.tuning import broadcast_decisions

    entries = {gemv_key(8, 8, "float32"): {"kernel": "pallas", "bm": 8}}
    monkeypatch.setattr(jax, "process_count", lambda: 2)

    sent = []
    monkeypatch.setattr(
        multihost_utils, "broadcast_one_to_all",
        lambda v: sent.append(np.asarray(v)) or np.asarray(v),
    )
    # Coordinator side: broadcasts its (loaded) entries.
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    coord = TuningCache(cache_path)
    coord.entries = dict(entries)
    assert broadcast_decisions(coord).entries == entries
    assert len(sent) == 2  # length, then payload

    # Worker side: starts EMPTY (never read the file), receives the
    # coordinator's payload from the same broadcast.
    replay = list(sent)
    monkeypatch.setattr(
        multihost_utils, "broadcast_one_to_all",
        lambda v: replay.pop(0),
    )
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    worker = TuningCache(cache_path)
    assert broadcast_decisions(worker).entries == entries


def test_get_cache_multihost_worker_skips_file_read(
    cache_path, monkeypatch
):
    """The singleton's multi-host path: only the coordinator touches the
    file; a worker gets the broadcast table even when its local file is
    poisoned."""
    import jax

    import matvec_mpi_multiplier_tpu.tuning as tuning

    cache_path.write_text("{ not json — a worker must never parse this")
    entries = {gemv_key(4, 4, "float32"): {"kernel": "xla"}}
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    monkeypatch.setattr(
        tuning, "broadcast_decisions",
        lambda cache: (cache.entries.update(entries), cache)[1],
    )
    reset_cache()
    assert tuning.get_cache().entries == entries


def test_save_multihost_only_coordinator_writes(cache_path, monkeypatch):
    import matvec_mpi_multiplier_tpu.parallel.distributed as dist

    cache = TuningCache.load(cache_path)
    cache.record(gemv_key(8, 8, "float32"), {"kernel": "xla"})
    monkeypatch.setattr(dist, "is_main_process", lambda: False)
    cache.save()
    assert not cache_path.exists()
    monkeypatch.setattr(dist, "is_main_process", lambda: True)
    cache.save()
    assert cache_path.exists()

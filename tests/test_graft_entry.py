"""Driver-entry contract tests (__graft_entry__.py).

The driver compile-checks ``entry()`` single-chip and runs
``dryrun_multichip(n)`` on a virtual CPU mesh; nothing in the suite pinned
either, so a refactor could silently break the driver handshake. Run in a
subprocess because ``dryrun_multichip`` must pin the platform/device count
BEFORE the backend initializes (the test process already holds an 8-device
CPU backend). 6 devices exercises the reference's non-trivial 2×3 grid
(``get_2_most_closest_multipliers`` semantics, ``src/utils.c:26-37``).
"""

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

SCRIPT = """
import __graft_entry__ as g

g.dryrun_multichip(6)  # pins cpu + 6 virtual devices, then one real step
print("dryrun6 ok")

import jax

fn, args = g.entry()
jax.jit(fn).lower(*args).compile()
print("entry compile ok")
"""


def test_entry_and_dryrun_2x3_grid():
    env = dict(os.environ, PYTHONPATH=str(REPO), XLA_FLAGS="",
               JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-c", SCRIPT], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "dryrun6 ok" in r.stdout
    assert "entry compile ok" in r.stdout

"""Ozaki-style split-matrix GEMV kernel: fp64-parity accuracy at MXU speed.

The ``compensated`` kernel answers the reference's fp64 end-to-end
accumulation (src/matr_utils.c:86-96) but is VPU-bound (~100-150x the XLA
dot, docs/COMPENSATED.md). ``ozaki`` must match its accuracy — the block dots of
8-bit-aligned slices are exact in fp32, so the only rounding is the shared
double-float combine — while doing the bulk arithmetic as one batched
contraction.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from matvec_mpi_multiplier_tpu import get_strategy, make_mesh
from matvec_mpi_multiplier_tpu.ops.compensated import gemv_compensated
from matvec_mpi_multiplier_tpu.ops.gemv import available_kernels, gemv_xla
from matvec_mpi_multiplier_tpu.ops.ozaki import (
    _BLOCK,
    _split_blocked,
    gemv_ozaki,
    gemv_ozaki6,
)


def _ulps(y, truth):
    t32 = truth.astype(np.float32)
    return np.abs(y.astype(np.float64) - truth) / np.spacing(np.abs(t32))


def test_registered():
    assert "ozaki" in available_kernels()
    assert "ozaki6" in available_kernels()


def test_split_is_exact_and_bf16_representable():
    """Slices must sum back to the input exactly when the in-block dynamic
    range fits the documented window (elements within 2^8 of the block
    max), and each slice must be bf16-exact — the two pillars of the
    exact-block-dot argument."""
    rng = np.random.default_rng(0)
    mag = rng.uniform(1e6, 1e7, (4, 2, _BLOCK))  # ratio 10 < 2^8
    sign = rng.choice([-1.0, 1.0], mag.shape)
    v = jnp.asarray((mag * sign).astype(np.float32))
    slices, shift = _split_blocked(v, 4)
    assert not np.any(np.asarray(shift))  # ordinary data: no prescale
    # bf16 round-trip is the identity: every slice is 8 significand bits.
    np.testing.assert_array_equal(
        np.asarray(slices.astype(jnp.bfloat16).astype(jnp.float32)),
        np.asarray(slices.astype(jnp.float32)),
    )
    recon = np.asarray(slices.astype(jnp.float32), np.float64).sum(0)
    np.testing.assert_array_equal(recon, np.asarray(v, np.float64))


def test_split_wide_range_residual_within_envelope():
    """Unbounded dynamic range (elements far below the block max) loses
    bits BELOW 2^(E - 8s) of the block max — never more: the documented
    graceful-degradation envelope."""
    rng = np.random.default_rng(7)
    v = rng.uniform(-1e7, 1e7, (4, 2, _BLOCK)).astype(np.float32)
    slices, _ = _split_blocked(jnp.asarray(v), 4)
    recon = np.asarray(slices.astype(jnp.float32), np.float64).sum(0)
    _, exp = np.frexp(np.abs(v).max(axis=-1, keepdims=True))
    bound = np.ldexp(1.0, exp - 8 * 4)  # 2^(E - 32), elementwise per block
    assert np.all(np.abs(recon - v.astype(np.float64)) <= bound)


def test_split_zero_block():
    slices, _ = _split_blocked(jnp.zeros((1, 1, _BLOCK), jnp.float32), 4)
    assert not np.any(np.asarray(slices.astype(jnp.float32)))
    assert np.all(np.isfinite(np.asarray(slices.astype(jnp.float32))))


def test_cancellation_stress_matches_fp64(devices):
    """The compensated-study stress case: interleaved ±1e6..1e7 pairs with
    O(1) true row sums — fp32 loses every significant bit, ozaki must match
    the fp64 oracle exactly (in-block range is far inside the 32-bit
    window, so the sliced representation is exact and so are the block
    dots; x = ones has one nonzero slice)."""
    rng = np.random.default_rng(11)
    m, k = 64, 2048
    big = rng.uniform(1e6, 1e7, size=(m, k // 2)).astype(np.float32)
    small = rng.uniform(-1.0, 1.0, size=(m, k // 2)).astype(np.float32)
    a = np.empty((m, k), np.float32)
    a[:, 0::2] = big + small
    a[:, 1::2] = -big
    x = np.ones(k, np.float32)
    oracle = a.astype(np.float64) @ x.astype(np.float64)
    plain = np.asarray(gemv_xla(jnp.asarray(a), jnp.asarray(x)))
    assert _ulps(plain, oracle).max() > 1e6  # fp32 is garbage here
    for fn in (gemv_ozaki, gemv_ozaki6):
        y = np.asarray(fn(jnp.asarray(a), jnp.asarray(x)))
        assert _ulps(y, oracle).max() <= 2.0


def test_random_matches_compensated_bitwise_class(devices):
    """On well-scaled random data ozaki and compensated must both sit within
    ~1 ulp of the fp64 oracle (they share the double-float combine; the
    paths differ only in where exactness comes from)."""
    rng = np.random.default_rng(1)
    m, k = 64, 4096 + 100  # non-multiple of _BLOCK: exercises the padding
    a64 = rng.standard_normal((m, k))
    x64 = rng.standard_normal(k)
    a32 = jnp.asarray(a64, jnp.float32)
    x32 = jnp.asarray(x64, jnp.float32)
    oracle = np.asarray(a32, np.float64) @ np.asarray(x32, np.float64)
    oz = np.asarray(gemv_ozaki(a32, x32))
    comp = np.asarray(gemv_compensated(a32, x32))
    assert _ulps(oz, oracle).max() <= 2.0
    assert _ulps(comp, oracle).max() <= 2.0


def test_long_contraction_beats_plain_fp32(devices):
    rng = np.random.default_rng(2)
    m, k = 8, 1 << 15
    a64 = rng.uniform(0.0, 10.0, (m, k))
    x64 = rng.uniform(0.0, 10.0, k)
    truth = (
        np.asarray(a64, np.float32).astype(np.float64)
        @ np.asarray(x64, np.float32).astype(np.float64)
    )
    a32 = jnp.asarray(a64, jnp.float32)
    x32 = jnp.asarray(x64, jnp.float32)
    plain = np.asarray(gemv_xla(a32, x32))
    oz = np.asarray(gemv_ozaki(a32, x32))
    assert _ulps(oz, truth).max() <= 2.0
    assert _ulps(oz, truth).max() * 10 < _ulps(plain, truth).max()


@pytest.mark.parametrize("name", ["rowwise", "colwise", "blockwise"])
def test_strategies_with_ozaki_kernel(devices, name):
    rng = np.random.default_rng(3)
    m, k = 64, 512
    a64 = rng.uniform(0.0, 10.0, (m, k))
    x64 = rng.uniform(0.0, 10.0, k)
    mesh = make_mesh(8)
    fn = get_strategy(name).build(mesh, kernel="ozaki")
    y = np.asarray(
        fn(jnp.asarray(a64, jnp.float32), jnp.asarray(x64, jnp.float32))
    )
    assert _ulps(y, a64 @ x64).max() <= 4.0


def test_bf16_inputs_upcast_exactly(devices):
    rng = np.random.default_rng(4)
    a = jnp.asarray(rng.standard_normal((16, 512)), jnp.bfloat16)
    x = jnp.asarray(rng.standard_normal(512), jnp.bfloat16)
    oracle = np.asarray(a, np.float64) @ np.asarray(x, np.float64)
    y = np.asarray(gemv_ozaki(a, x))
    assert y.dtype == np.float32  # accumulator dtype contract (ops/gemv.py)
    assert _ulps(y, oracle).max() <= 2.0


def test_fp64_inputs_use_plain_fp64_dot(devices):
    rng = np.random.default_rng(5)
    a = rng.uniform(0.0, 10.0, (8, 128))
    x = rng.uniform(0.0, 10.0, 128)
    y = np.asarray(gemv_ozaki(jnp.asarray(a), jnp.asarray(x)))
    assert y.dtype == np.float64
    np.testing.assert_allclose(y, a @ x, rtol=1e-15)


def test_empty_contraction(devices):
    y = np.asarray(
        gemv_ozaki(jnp.zeros((4, 0), jnp.float32), jnp.zeros((0,), jnp.float32))
    )
    np.testing.assert_array_equal(y, np.zeros(4, np.float32))


def test_short_contraction_single_padded_block(devices):
    # k < _BLOCK: one zero-padded block must still be exact.
    rng = np.random.default_rng(6)
    a = jnp.asarray(rng.standard_normal((4, 7)), jnp.float32)
    x = jnp.asarray(rng.standard_normal(7), jnp.float32)
    oracle = np.asarray(a, np.float64) @ np.asarray(x, np.float64)
    y = np.asarray(gemv_ozaki(a, x))
    assert _ulps(y, oracle).max() <= 1.0


def test_exponent_extremes_no_nan(devices):
    """Finite inputs across the whole fp32 exponent range must never yield
    inf/NaN: blocks outside the slicing window are exactly prescaled in and
    the power-of-two correction is undone on the block dots."""
    cases = [
        3.4e38,   # near fp32 max: the q=256 carry would be 2^128 unscaled
        2.0**-120,  # far below the unscaled window: scales would flush
        np.float32(np.finfo(np.float32).tiny),  # min normal
    ]
    for mag in cases:
        a = np.zeros((1, _BLOCK), np.float32)
        a[0, 0] = mag
        x = np.ones(_BLOCK, np.float32)
        y = np.asarray(gemv_ozaki(jnp.asarray(a), jnp.asarray(x)))
        oracle = a.astype(np.float64) @ x.astype(np.float64)
        assert np.all(np.isfinite(y)), (mag, y)
        np.testing.assert_allclose(y, oracle.astype(np.float32), rtol=1e-6)
    # Mixed extremes: huge a against tiny x — true value is O(1).
    a = np.full((2, _BLOCK), 1e30, np.float32)
    x = np.full(_BLOCK, 1e-30, np.float32)
    y = np.asarray(gemv_ozaki(jnp.asarray(a), jnp.asarray(x)))
    oracle = a.astype(np.float64) @ x.astype(np.float64)
    np.testing.assert_allclose(y, oracle, rtol=1e-6)


def test_gather_output_rejects_unknown_string(devices):
    mesh = make_mesh(2)
    with pytest.raises(ValueError, match="ring"):
        get_strategy("rowwise").build(mesh, gather_output="rings")

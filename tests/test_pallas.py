"""Pallas GEMV kernel tests (interpret mode on the CPU backend).

The same kernel code runs compiled on TPU; interpret mode validates indexing,
accumulation, and the registry fallback logic on the virtual-device CI path.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from matvec_mpi_multiplier_tpu import get_strategy, make_mesh
from matvec_mpi_multiplier_tpu.ops import pallas_gemv  # registers "pallas"
from matvec_mpi_multiplier_tpu.ops.gemv import get_kernel
from matvec_mpi_multiplier_tpu.ops.pallas_gemv import (
    _largest_divisor_leq,
    gemv_pallas,
)


def test_largest_divisor():
    assert _largest_divisor_leq(1024, 256, 16) == 256
    assert _largest_divisor_leq(48, 256, 16) == 48
    assert _largest_divisor_leq(40, 256, 16) is None  # no divisor is 16-aligned
    assert _largest_divisor_leq(4, 256, 16) is None
    assert _largest_divisor_leq(60000, 1024, 128) is None  # 60000 % 128 != 0


@pytest.mark.parametrize("shape", [(256, 1024), (16, 128), (48, 256), (512, 2048)])
def test_pallas_matches_numpy(rng, shape):
    a = rng.standard_normal(shape).astype(np.float32)
    x = rng.standard_normal(shape[1]).astype(np.float32)
    y = np.asarray(gemv_pallas(jnp.asarray(a), jnp.asarray(x)))
    np.testing.assert_allclose(y, a @ x, rtol=2e-5, atol=2e-4)


def test_pallas_multi_tile_accumulation(rng):
    """k spans several bk tiles: accumulation across grid steps must be exact."""
    a = rng.standard_normal((32, 4096)).astype(np.float32)
    x = rng.standard_normal(4096).astype(np.float32)
    y = np.asarray(gemv_pallas(jnp.asarray(a), jnp.asarray(x)))
    np.testing.assert_allclose(y, a @ x, rtol=2e-5, atol=2e-4)


def test_pallas_fallback_tiny():
    """The 4×8 fixture can't tile; must silently use the XLA kernel."""
    a = jnp.ones((4, 8), jnp.float32)
    x = jnp.ones((8,), jnp.float32)
    y = np.asarray(gemv_pallas(a, x))
    np.testing.assert_allclose(y, np.full(4, 8.0))


def test_pallas_bf16(rng):
    a = rng.standard_normal((64, 256)).astype(np.float32)
    x = rng.standard_normal(256).astype(np.float32)
    y = gemv_pallas(jnp.asarray(a, jnp.bfloat16), jnp.asarray(x, jnp.bfloat16))
    # Kernel contract: accumulator dtype out (fp32 for bf16 storage).
    assert y.dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(y, np.float32), a @ x, rtol=0.05, atol=0.5
    )


def test_registry_has_pallas():
    assert get_kernel("pallas") is gemv_pallas


@pytest.mark.parametrize("name", ["rowwise", "colwise", "blockwise"])
def test_strategies_with_pallas_kernel(devices, rng, name):
    """End-to-end: sharded strategies running the Pallas kernel per device."""
    a = rng.standard_normal((64, 256)).astype(np.float32)
    x = rng.standard_normal(256).astype(np.float32)
    mesh = make_mesh(4)
    strat = get_strategy(name)
    y = np.asarray(strat.build(mesh, kernel="pallas")(jnp.asarray(a), jnp.asarray(x)))
    np.testing.assert_allclose(y, a @ x, rtol=2e-5, atol=2e-4)

"""utils/compat.py: the JAX cross-version shim.

The shim must present ONE working surface on both API generations: the
new-API names (vma system) where the install has them, and faithful
fallbacks (check_rep, psum-based axis size, no-op vma handling) on older
installs. Generation-specific behavior is covered by skip-marked tests so
the suite documents both sides wherever it runs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from matvec_mpi_multiplier_tpu.parallel.mesh import make_1d_mesh
from matvec_mpi_multiplier_tpu.utils import compat


def test_generation_flag_matches_install():
    assert compat.HAS_VMA == (
        hasattr(jax, "typeof") and hasattr(jax.lax, "pcast")
    )


def test_shard_map_runs_a_psum_body(devices):
    mesh = make_1d_mesh(8, axis_name="r")
    f = jax.jit(
        compat.shard_map(
            lambda x: jax.lax.psum(x, "r"),
            mesh=mesh, in_specs=(P("r"),), out_specs=P(),
        )
    )
    # Local blocks are (1,); the replicated output keeps the body's shape.
    out = np.asarray(f(jnp.arange(8.0)))
    np.testing.assert_allclose(out, np.array([28.0]))


def test_shard_map_check_vma_false_accepted(devices):
    # ppermute output replication can't be proven by either generation's
    # checker; check_vma=False must map onto the local spelling.
    mesh = make_1d_mesh(8, axis_name="r")
    perm = [(i, (i + 1) % 8) for i in range(8)]
    f = jax.jit(
        compat.shard_map(
            lambda x: jax.lax.ppermute(x, "r", perm),
            mesh=mesh, in_specs=(P("r"),), out_specs=P("r"),
            check_vma=False,
        )
    )
    out = np.asarray(f(jnp.arange(8.0)))
    np.testing.assert_allclose(np.sort(out), np.arange(8.0))


def test_axis_size_is_static_inside_shard_map(devices):
    mesh = make_1d_mesh(8, axis_name="r")
    seen = []

    def body(x):
        p = compat.axis_size("r")
        seen.append(p)
        return x

    jax.jit(
        compat.shard_map(body, mesh=mesh, in_specs=(P("r"),), out_specs=P("r"))
    )(jnp.arange(8.0))
    assert seen and all(int(p) == 8 for p in seen)
    # Static: usable as a Python loop bound at trace time.
    assert all(isinstance(int(p), int) for p in seen)


def test_vma_of_returns_frozenset():
    assert compat.vma_of(jnp.ones(3)) == frozenset()


def test_pcast_identity_on_empty_axes():
    x = jnp.ones(3)
    assert compat.pcast_to_varying(x, ()) is x


def test_shape_dtype_struct_drops_or_keeps_vma():
    s = compat.shape_dtype_struct((4, 2), jnp.float32, vma=frozenset())
    assert s.shape == (4, 2)
    assert s.dtype == jnp.float32


@pytest.mark.skipif(
    compat.HAS_VMA, reason="old-generation fallback path (no vma system)"
)
def test_old_jax_vma_handling_is_noop(devices):
    # On the pre-vma generation the alignment dance must vanish entirely.
    x = jnp.ones(3)
    assert compat.align_vma(x)[0] is x
    assert compat.pcast_to_varying(x, ("r",)) is x


@pytest.mark.skipif(
    not compat.HAS_VMA, reason="needs the vma system (new JAX)"
)
def test_new_jax_vma_alignment_marks_axes(devices):
    # Under shard_map a replicated operand aligned against a varying one
    # must come back marked varying on the union of axes.
    mesh = make_1d_mesh(8, axis_name="r")
    seen = []

    def body(a, x):
        a2, x2 = compat.align_vma(a, x)
        seen.append((compat.vma_of(a2), compat.vma_of(x2)))
        return a2 * x2

    jax.jit(
        compat.shard_map(
            body, mesh=mesh, in_specs=(P("r"), P()), out_specs=P("r")
        )
    )(jnp.arange(8.0), jnp.ones(()))
    vma_a, vma_x = seen[0]
    assert vma_a == vma_x
    assert "r" in vma_x

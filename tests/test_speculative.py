"""Speculative quantized dispatch acceptance (ISSUE 16).

The two-tier serving contract (docs/QUANTIZATION.md "speculative
serving"): ``engine.submit(x, rtol=...)`` on a speculative-armed engine
serves the int8c candidate fused with the seeded sampled-projection
check, and the verdict settles at ``result()`` — accept keeps the
candidate, a miss IS a traced native re-dispatch. Four behavioral
guarantees pinned here:

* **Never a silent wrong answer** — adversarial operands built to break
  the int8c grid (catastrophic cancellation: ``y = Ax ≈ 0`` while the
  quantization error stays at the grid scale) MUST escalate, and the
  escalated answer is bitwise the native engine's.
* **No speculation tax on exact requests** — ``rtol=None`` through an
  armed engine is bitwise-identical to a plain native engine.
* **Determinism** — the probe set is seeded (`ops/speculative.py::
  SPEC_SEED`), so two independently constructed engines reach identical
  verdicts on identical streams.
* **Typed refusal under chaos** — a poisoned speculative candidate
  raises ``ResultIntegrityError`` (the gate is FORCED on speculative
  futures), never serves.

Plus the serving discipline: a 200-request mixed rtol/exact
mixed-width stream over a warmed engine compiles nothing.
"""

import numpy as np
import pytest

from matvec_mpi_multiplier_tpu import make_mesh
from matvec_mpi_multiplier_tpu.engine import MatvecEngine
from matvec_mpi_multiplier_tpu.ops.speculative import (
    SPEC_RTOL_FLOOR,
    eligible,
    probe_count,
    probe_matrix,
)
from matvec_mpi_multiplier_tpu.resilience import (
    FaultPlan,
    FaultSpec,
    ResultIntegrityError,
)
from matvec_mpi_multiplier_tpu.utils.errors import ConfigError

M, K = 64, 256
RTOL = 1e-3


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def _well_conditioned(seed=0):
    rng = np.random.default_rng(seed)
    a = rng.uniform(0.0, 10.0, (M, K)).astype(np.float32)
    x = rng.uniform(0.0, 10.0, K).astype(np.float32)
    return a, x


def _adversarial(seed=3):
    """Operands the int8c tier cannot serve within RTOL: project A's
    rows against x so the true product nearly cancels (``Ax ≈ 0``)
    while each row keeps O(1) entries — the quantization error stays at
    the grid scale, so the RELATIVE error of the candidate explodes and
    the check must reject."""
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((M, K)).astype(np.float64)
    x = rng.standard_normal(K).astype(np.float64)
    a = a - np.outer(a @ x, x) / float(x @ x)
    return a.astype(np.float32), x.astype(np.float32)


def _engine(a, mesh, **kw):
    kw.setdefault("strategy", "rowwise")
    kw.setdefault("promote", 2)
    kw.setdefault("max_bucket", 8)
    return MatvecEngine(a, mesh, dtype_storage="speculate", **kw)


# ------------------------------------------------- acceptance contract


def test_well_conditioned_stream_never_escalates(mesh):
    a, x = _well_conditioned()
    engine = _engine(a, mesh)
    oracle = a.astype(np.float64) @ x.astype(np.float64)
    for _ in range(5):
        y = engine.submit(x, rtol=RTOL).result()
        rel = np.linalg.norm(y - oracle) / np.linalg.norm(oracle)
        assert rel <= RTOL
    h = engine.health()
    assert h["counters"]["speculative_dispatches"] == 5
    assert h["counters"]["escalations"] == 0
    assert h["storage"]["escalation_rate"] == 0.0
    assert h["storage"]["speculative"] is True


def test_adversarial_operand_escalates_and_answer_is_native(mesh):
    a, x = _adversarial()
    spec = _engine(a, mesh)
    plain = MatvecEngine(a, mesh, strategy="rowwise", promote=2,
                         max_bucket=8)
    y = spec.submit(x, rtol=RTOL).result()
    h = spec.health()
    assert h["counters"]["speculative_dispatches"] == 1
    assert h["counters"]["escalations"] == 1, (
        "the cancellation operand must fail the on-device check"
    )
    assert h["storage"]["escalation_rate"] == 1.0
    # The escalated answer IS the native answer — bitwise, not approx.
    np.testing.assert_array_equal(y, plain.submit(x).result())


def test_gemm_block_escalates_per_chunk(mesh):
    a, x = _adversarial()
    engine = _engine(a, mesh)
    plain = MatvecEngine(a, mesh, strategy="rowwise", promote=2,
                         max_bucket=8)
    xb = np.stack([x, x + np.float32(0.25), 2 * x], axis=1)
    y = engine.submit(xb, rtol=RTOL).result()
    assert y.shape == (M, 3)
    h = engine.health()
    assert h["counters"]["escalations"] >= 1
    np.testing.assert_array_equal(y, plain.submit(xb).result())


def test_rtol_none_is_bitwise_native(mesh):
    a, x = _well_conditioned(seed=1)
    armed = _engine(a, mesh)
    plain = MatvecEngine(a, mesh, strategy="rowwise", promote=2,
                         max_bucket=8)
    y_armed = armed.submit(x).result()
    np.testing.assert_array_equal(y_armed, plain.submit(x).result())
    assert armed.health()["counters"]["speculative_dispatches"] == 0


def test_sub_floor_rtol_serves_native(mesh):
    a, x = _well_conditioned(seed=2)
    engine = _engine(a, mesh)
    tight = SPEC_RTOL_FLOOR / 10.0
    assert not eligible(tight)
    y = engine.submit(x, rtol=tight).result()
    assert engine.health()["counters"]["speculative_dispatches"] == 0
    np.testing.assert_allclose(
        y, a.astype(np.float64) @ x.astype(np.float64), rtol=1e-5
    )


def test_nonpositive_rtol_rejected(mesh):
    a, x = _well_conditioned(seed=2)
    engine = _engine(a, mesh)
    with pytest.raises(ConfigError):
        engine.submit(x, rtol=0.0)
    with pytest.raises(ConfigError):
        engine.submit(x, rtol=-1e-3)


# ------------------------------------------------------- determinism


def test_probe_set_is_seeded_and_shared():
    s = probe_count(SPEC_RTOL_FLOOR)
    np.testing.assert_array_equal(
        probe_matrix(s, M, np.float32), probe_matrix(s, M, np.float32)
    )


def test_verdicts_deterministic_across_engines(mesh):
    """Two independently constructed engines draw the same probes
    (SPEC_SEED), so a given request meets the same verdict in both —
    speculation is reproducible, not a per-process coin flip."""
    a_bad, x_bad = _adversarial()
    a_ok, x_ok = _well_conditioned()
    for a, x, esc in ((a_bad, x_bad, 1), (a_ok, x_ok, 0)):
        e1, e2 = _engine(a, mesh), _engine(a, mesh)
        y1 = e1.submit(x, rtol=RTOL).result()
        y2 = e2.submit(x, rtol=RTOL).result()
        np.testing.assert_array_equal(y1, y2)
        assert e1.health()["counters"]["escalations"] == esc
        assert e2.health()["counters"]["escalations"] == esc


# ------------------------------------------------------------- chaos


@pytest.mark.chaos
def test_poisoned_candidate_fails_typed_never_served(mesh):
    """A silently corrupted speculative candidate must raise
    ``ResultIntegrityError`` at result() even with the optional
    integrity gate OFF — the caller declared a tolerance, so the gate
    is forced on speculative futures (engine/core.py::submit)."""
    a, x = _well_conditioned()
    engine = _engine(
        a, mesh,
        fault_plan=FaultPlan(
            [FaultSpec(site="dispatch", kind="nan", times=1)]
        ),
    )
    assert engine.integrity_gate is False
    fut = engine.submit(x, rtol=RTOL)
    with pytest.raises(ResultIntegrityError):
        fut.result()
    h = engine.health()
    assert h["counters"]["integrity_failures"] == 1
    # The refusal is cached, not re-counted; the stream recovers.
    with pytest.raises(ResultIntegrityError):
        fut.result()
    assert engine.health()["counters"]["integrity_failures"] == 1
    y = engine.submit(x, rtol=RTOL).result()
    assert np.all(np.isfinite(y))


# ------------------------------------------------- serving discipline


def test_mixed_stream_compiles_nothing_after_warmup(mesh):
    """200 requests mixing exact (rtol=None) and speculative traffic
    across the width mix: zero steady-phase compiles — both tiers ride
    the warmed ExecKey set, and escalations re-dispatch through already
    -compiled native executables."""
    a, _ = _well_conditioned()
    engine = _engine(a, mesh)
    widths = (1, 2, 3, 4, 6, 8)
    engine.warmup(widths)
    rng = np.random.default_rng(7)
    pool = {
        w: rng.uniform(0.0, 10.0, (K, w)).astype(np.float32)
        for w in widths
    }
    # Cover every (width, tier) pair once inside the warm phase. An
    # escalation needs no executable of its own: the miss re-dispatches
    # through the same native ExecKeys the exact submissions warm here.
    warm = []
    for w in widths:
        xw = pool[w][:, 0] if w == 1 else pool[w]
        warm.append(engine.submit(xw))
        warm.append(engine.submit(xw, rtol=RTOL))
    for f in warm:
        f.result()
    compiles_warm = engine.stats.compiles

    futures = []
    for i, w in enumerate(rng.choice(widths, size=200)):
        xw = pool[w][:, 0] if w == 1 else pool[w]
        futures.append(
            engine.submit(xw, rtol=RTOL if i % 2 else None)
        )
    for f in futures:
        f.result()
    h = engine.health()
    assert engine.stats.compiles == compiles_warm, (
        "steady phase must be compile-free across both tiers"
    )
    assert h["counters"]["speculative_dispatches"] > 0
    assert h["counters"]["escalations"] == 0

"""The native tier must self-build in any checkout with a C++ toolchain.

Round-2 review finding: 14 native tests skipped silently unless
``make -C native`` had been run by hand. ``ensure_built`` (called from
conftest.py at collection time) closes that hole; these tests pin it.
"""

import shutil

import pytest

from matvec_mpi_multiplier_tpu.utils.native_lib import ensure_built, lib_path


def test_ensure_built_succeeds_with_toolchain():
    if shutil.which("make") is None or shutil.which("g++") is None:
        pytest.skip("no C++ toolchain on this host")
    assert ensure_built()
    assert lib_path().exists()


def test_override_env_is_never_built_over(monkeypatch, tmp_path):
    missing = tmp_path / "nope" / "lib.so"
    monkeypatch.setenv("MATVEC_NATIVE_LIB", str(missing))
    assert ensure_built() is False
    assert not missing.exists()


def test_stale_library_is_rebuilt():
    """A .so older than any native source must be rebuilt (a checkout built
    before a new kernel file existed would otherwise export a library
    missing its symbols forever)."""
    import os
    import shutil as sh

    if sh.which("make") is None or sh.which("g++") is None:
        pytest.skip("no C++ toolchain on this host")
    assert ensure_built()
    lib = lib_path()
    old = 1.0  # epoch: unconditionally older than every source file
    os.utime(lib, (old, old))  # pretend the build predates the sources
    before = lib.stat().st_mtime
    assert ensure_built()
    assert lib.stat().st_mtime > before  # rebuilt, not short-circuited


def test_corrupt_library_is_not_loaded(monkeypatch, tmp_path, capsys):
    """A truncated/garbage .so must degrade to 'not built', not crash the
    import chain (ctypes.CDLL raises OSError on it)."""
    from matvec_mpi_multiplier_tpu.utils import native_lib

    garbage = tmp_path / "libmatvec_gemv.so"
    garbage.write_bytes(b"\x7fELFnot-really-an-elf")
    monkeypatch.setenv("MATVEC_NATIVE_LIB", str(garbage))
    monkeypatch.setattr(native_lib, "_lib", None)
    assert native_lib.load_library() is None
    assert "unloadable" in capsys.readouterr().err

"""Host-link measurement and derived reference-mode (Q5 substitute) tests."""

import dataclasses

import numpy as np
import pytest

from matvec_mpi_multiplier_tpu import get_strategy, make_mesh
from matvec_mpi_multiplier_tpu.bench.hostlink import (
    LinkModel,
    derive_reference_result,
    measure_link,
    operand_bytes,
)
from matvec_mpi_multiplier_tpu.bench.timing import (
    TimingResult,
    benchmark_strategy,
)


def _result(**kw):
    base = dict(
        n_rows=64, n_cols=32, n_devices=1, strategy="rowwise",
        dtype="float32", mode="amortized", measure="chain",
        mean_time_s=0.5, times_s=(0.5,), n_reps=1,
    )
    base.update(kw)
    return TimingResult(**base)


def test_link_model_math():
    link = LinkModel(alpha_s=0.001, bps=1e9, samples=())
    assert link.transfer_time_s(0) == pytest.approx(0.001)
    assert link.transfer_time_s(10**9) == pytest.approx(1.001)
    assert link.gbps == pytest.approx(1.0)


def test_operand_bytes_matvec_and_gemm():
    assert operand_bytes(_result()) == 4 * (64 * 32 + 32)
    assert operand_bytes(_result(n_rhs=8)) == 4 * (64 * 32 + 32 * 8)
    assert operand_bytes(_result(dtype="bfloat16")) == 2 * (64 * 32 + 32)


def test_derive_reference_result():
    link = LinkModel(alpha_s=0.01, bps=1e9, samples=())
    derived = derive_reference_result(_result(), link)
    assert derived.mode == "reference_derived"
    assert derived.measure == "derived"
    expected = 0.5 + 0.01 + 4 * (64 * 32 + 32) / 1e9
    assert derived.mean_time_s == pytest.approx(expected)
    # Everything else carries over.
    assert derived.strategy == "rowwise"
    assert derived.n_reps == 1


def test_derive_rejects_reference_input():
    link = LinkModel(alpha_s=0.0, bps=1e9, samples=())
    with pytest.raises(ValueError, match="amortized"):
        derive_reference_result(_result(mode="reference"), link)


def test_measure_link_cpu(devices):
    # Small bounded ladder on the CPU backend: sane, positive fit.
    ladder = [2**16, 2**18, 2**20]
    link = measure_link(ladder, reps=2)
    assert link.bps > 0
    assert link.alpha_s >= 0
    assert len(link.samples) == 3
    assert all(t > 0 for _, t in link.samples)
    # The model must roughly reproduce its own largest sample (the fit is a
    # 2-parameter line through 3 monotone points).
    n, t = link.samples[-1]
    assert link.transfer_time_s(n) == pytest.approx(t, rel=2.0, abs=1e-2)


def test_derived_agrees_with_literal_reference_cpu(devices, rng):
    # On the CPU backend the literal per-rep protocol is safe — the derived
    # substitute must land in the same ballpark (it is the sum of the same
    # two components, one measured, one modeled).
    mesh = make_mesh(4)
    strat = get_strategy("rowwise")
    a = rng.standard_normal((128, 64))
    x = rng.standard_normal(64)
    amortized = benchmark_strategy(
        strat, mesh, a, x, n_reps=3, mode="amortized", measure="sync"
    )
    literal = benchmark_strategy(
        strat, mesh, a, x, n_reps=3, mode="reference", measure="sync"
    )
    link = measure_link([2**16, 2**18, 2**20], reps=2)
    derived = derive_reference_result(amortized, link)
    # Generous bound: both include the same compute; the transfer here is
    # microseconds. Factor-5 catches an order-of-magnitude modeling bug
    # without flaking on scheduler noise.
    assert derived.mean_time_s < 5 * literal.mean_time_s
    assert literal.mean_time_s < 5 * derived.mean_time_s


def test_measure_link_input_validation():
    from matvec_mpi_multiplier_tpu.utils.errors import ConfigError

    with pytest.raises(ConfigError, match="ladder"):
        measure_link([])
    with pytest.raises(ConfigError, match="ladder"):
        measure_link([0])
    with pytest.raises(ConfigError, match="reps"):
        measure_link([2**16], reps=0)


def test_hostlink_study_cli(devices, tmp_path, monkeypatch):
    # End-to-end: amortized rows in, derived rows appended to their own
    # per-strategy file (never the literal reference one); re-runs are
    # idempotent per config.
    from matvec_mpi_multiplier_tpu.bench.metrics import append_result, csv_path, read_csv

    append_result(_result(mean_time_s=0.001), tmp_path)
    import sys

    sys.path.insert(0, "/root/repo/scripts")
    import hostlink_study

    argv = ["--data-root", str(tmp_path), "--max-mb", "1", "--reps", "1"]
    assert hostlink_study.main(argv) == 0
    derived_path = csv_path("rowwise", tmp_path, mode="reference_derived")
    rows = read_csv(derived_path)
    assert rows and rows[0]["time"] >= 0.001
    # Literal-reference file untouched: modeled and measured rows never mix.
    assert not csv_path("rowwise", tmp_path, mode="reference").exists()
    ext_rows = read_csv(tmp_path / "out" / "results_extended.csv")
    derived = [r for r in ext_rows if r["measure"] == "derived"]
    assert len(derived) == 1
    assert derived[0]["mode"] == "reference_derived"
    # Second run: no duplicate derived rows.
    assert hostlink_study.main(argv) == 0
    assert len(read_csv(derived_path)) == 1

"""Staged `overlap` schedule family tests.

The acceptance contract (ISSUE 3): every (strategy, S) overlap variant must
be allclose-equivalent to that strategy's ``gather`` baseline on the CPU
mesh, selectable via ``build(combine="overlap")`` and as a
``combine="auto"`` candidate, with the stage count S resolved from the
tuning cache's fifth axis (``tune_overlap``, schema v3) when not pinned.
Covers the staged primitives (``parallel/ring.py``), the strategy-level
wiring (``models/``), the tuner axis (``tuning/``), the serving engine's
stage pinning (``engine/``), and the fused Pallas collective GEMV
(``ops/pallas_collective.py``, interpret mode on this CPU mesh).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from matvec_mpi_multiplier_tpu import build_gemm, get_strategy, make_mesh
from matvec_mpi_multiplier_tpu.engine import MatvecEngine
from matvec_mpi_multiplier_tpu.models.base import DEFAULT_OVERLAP_STAGES
from matvec_mpi_multiplier_tpu.ops.gemv import gemv_xla
from matvec_mpi_multiplier_tpu.parallel.mesh import make_1d_mesh
from matvec_mpi_multiplier_tpu.parallel.ring import (
    stage_ladder,
    staged_overlap_gather,
    staged_overlap_scatter,
)
from matvec_mpi_multiplier_tpu.tuning import (
    TuningCache,
    combine_key,
    lookup_overlap,
    overlap_key,
    reset_cache,
)
from matvec_mpi_multiplier_tpu.utils.compat import shard_map
from matvec_mpi_multiplier_tpu.utils.errors import ShardingError

OVERLAP_STRATEGIES = ("rowwise", "colwise", "blockwise")


@pytest.fixture()
def cache_path(tmp_path, monkeypatch):
    path = tmp_path / "tuning_cache.json"
    monkeypatch.setenv("MATVEC_TUNING_CACHE", str(path))
    reset_cache()
    yield path
    reset_cache()


# ------------------------------------------------------------- primitives


def test_stage_ladder():
    assert stage_ladder(64, 8) == [8, 4, 2, 1]
    assert stage_ladder(48, 8) == [2, 1]  # chunk 6: only 2 and 1 divide
    assert stage_ladder(60, 8) == []      # 60 % 8 != 0: no overlap at all
    assert stage_ladder(8, 8) == [1]


@pytest.mark.parametrize("step", ["psum_scatter", "ring"])
@pytest.mark.parametrize("stages", [1, 2, 4, 8])
def test_staged_scatter_matches_unstaged(devices, rng, stages, step):
    """Both per-stage combine flavors, at every ladder depth, must agree
    with the un-staged reduce-scatter of the full local partial."""
    mesh = make_1d_mesh(8, axis_name="d")
    m, k = 64, 32
    a = rng.standard_normal((m, k))
    x = rng.standard_normal(k)

    ours = jax.jit(shard_map(
        lambda ap, xs: staged_overlap_scatter(
            ap, xs, ("d",), gemv_xla, stages, step
        ),
        mesh=mesh, in_specs=(P(None, "d"), P("d")), out_specs=P("d"),
        check_vma=False,
    ))(a, x)
    theirs = jax.jit(shard_map(
        lambda ap, xs: jax.lax.psum_scatter(
            gemv_xla(ap, xs), "d", tiled=True
        ),
        mesh=mesh, in_specs=(P(None, "d"), P("d")), out_specs=P("d"),
    ))(a, x)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(theirs),
                               rtol=1e-12)
    np.testing.assert_allclose(np.asarray(ours), a @ x, rtol=1e-12)


def test_staged_scatter_batched(devices, rng):
    """The walk is rank-agnostic: a (k/p, b) RHS block rides it unchanged."""
    mesh = make_1d_mesh(8, axis_name="d")
    a = rng.standard_normal((64, 32))
    b = rng.standard_normal((32, 5))
    c = jax.jit(shard_map(
        lambda ap, bs: staged_overlap_scatter(
            ap, bs, ("d",), lambda A, B: A @ B, 4, "ring"
        ),
        mesh=mesh, in_specs=(P(None, "d"), P("d", None)),
        out_specs=P("d", None), check_vma=False,
    ))(a, b)
    np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-12)


@pytest.mark.parametrize("stages", [1, 2, 4])
def test_staged_gather_matches_full(devices, rng, stages):
    mesh = make_1d_mesh(8, axis_name="d")
    a = rng.standard_normal((64, 32))
    x = rng.standard_normal(32)
    y = jax.jit(shard_map(
        lambda ab, xf: staged_overlap_gather(ab, xf, ("d",), gemv_xla, stages),
        mesh=mesh, in_specs=(P("d", None), P()), out_specs=P(),
        check_vma=False,
    ))(a, x)
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-12)


def test_staged_scatter_rejects_indivisible(devices):
    mesh = make_1d_mesh(8, axis_name="d")
    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(shard_map(
            lambda ap, xs: staged_overlap_scatter(
                ap, xs, ("d",), gemv_xla, 4
            ),
            mesh=mesh, in_specs=(P(None, "d"), P("d")), out_specs=P("d"),
            check_vma=False,
        ))(np.ones((48, 16)), np.ones(16))  # chunk 6 % 4 != 0


# ---------------------------------------------- strategies: the contract


@pytest.mark.parametrize("name", OVERLAP_STRATEGIES)
@pytest.mark.parametrize("stages", [1, 2, 4, 8])
def test_overlap_allclose_gather_baseline(devices, rng, name, stages):
    """The acceptance criterion: every (strategy, S) overlap variant is
    allclose to the gather baseline on the CPU mesh."""
    m, k = 64, 32
    a = rng.standard_normal((m, k))
    x = rng.standard_normal(k)
    mesh = make_mesh(8)
    strat = get_strategy(name)
    baseline = np.asarray(strat.build(mesh)(jnp.asarray(a), jnp.asarray(x)))
    y = np.asarray(
        strat.build(mesh, combine="overlap", stages=stages)(
            jnp.asarray(a), jnp.asarray(x)
        )
    )
    np.testing.assert_allclose(y, baseline, rtol=1e-12)
    np.testing.assert_allclose(y, a @ x, rtol=1e-10)


@pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
def test_overlap_across_mesh_sizes(devices, rng, n_dev):
    a = rng.standard_normal((32, 32))
    x = rng.standard_normal(32)
    mesh = make_mesh(n_dev)
    for name in OVERLAP_STRATEGIES:
        y = get_strategy(name).build(mesh, combine="overlap", stages=2)(
            jnp.asarray(a), jnp.asarray(x)
        )
        np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-10), name


def test_overlap_fixture(devices, fixture_4x8):
    """The committed 4x8 correctness fixture through the staged schedules
    (4 rows: the stage ladder clamps hard)."""
    from tests.conftest import FIXTURE_PRODUCT

    a, x = fixture_4x8
    mesh = make_mesh(2)
    for name in OVERLAP_STRATEGIES:
        y = get_strategy(name).build(mesh, combine="overlap", stages=4)(
            jnp.asarray(a), jnp.asarray(x)
        )
        np.testing.assert_allclose(np.asarray(y), FIXTURE_PRODUCT, rtol=1e-12)


def test_overlap_output_shardings(devices, rng):
    """The gather-family overlap replicates y (it IS the gather); the
    colwise overlap scatters it — and gather_output=False is never
    overridden by a gather-schedule combine."""
    a = rng.standard_normal((64, 64))
    x = rng.standard_normal(64)
    mesh = make_mesh(8)
    y = get_strategy("rowwise").build(mesh, combine="overlap", stages=2)(
        jnp.asarray(a), jnp.asarray(x)
    )
    assert y.sharding.is_fully_replicated
    y = get_strategy("colwise").build(
        mesh, combine="overlap", stages=2, gather_output=False
    )(jnp.asarray(a), jnp.asarray(x))
    assert y.sharding.spec == P(("rows", "cols"))
    # The sharded-output contract survives a gather-schedule combine.
    y = get_strategy("rowwise").build(
        mesh, combine="overlap", gather_output=False
    )(jnp.asarray(a), jnp.asarray(x))
    assert y.sharding.spec != P()


@pytest.mark.parametrize(
    "kernel", ["xla", "pallas", "compensated", "ozaki"]
)
def test_overlap_kernel_matrix(devices, rng, kernel):
    """The staged slabs reach every registered kernel tier (dynamic row
    slabs of 1/S the panel) — each must survive and stay correct."""
    a = rng.standard_normal((32, 32))
    x = rng.standard_normal(32)
    mesh = make_mesh(8)
    for name in ("colwise", "rowwise"):
        y = get_strategy(name).build(
            mesh, combine="overlap", stages=2, kernel=kernel
        )(jnp.asarray(a), jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-6), name


def test_overlap_reduced_precision(devices, rng):
    a = rng.standard_normal((32, 32)).astype(np.float32)
    x = rng.standard_normal(32).astype(np.float32)
    mesh = make_mesh(8)
    for dtype, rtol in (("float32", 1e-5), ("bfloat16", 0.03)):
        y = get_strategy("colwise").build(mesh, combine="overlap", stages=4)(
            jnp.asarray(a, dtype), jnp.asarray(x, dtype)
        )
        assert y.dtype == jnp.dtype(dtype)
        np.testing.assert_allclose(
            np.asarray(y, dtype=np.float32), a @ x, rtol=rtol, atol=rtol
        )


def test_colwise_overlap_registry_entry(devices, rng):
    a = rng.standard_normal((64, 64))
    x = rng.standard_normal(64)
    mesh = make_mesh(8)
    strat = get_strategy("colwise_overlap", stages=4)
    y = np.asarray(strat.build(mesh)(jnp.asarray(a), jnp.asarray(x)))
    np.testing.assert_allclose(y, a @ x, rtol=1e-10)
    assert strat.default_combine(mesh) == "overlap"


@pytest.mark.parametrize("stages", [1, 2, 8])
def test_overlap_ring_step_flavor(devices, rng, stages):
    """The double-buffered ring-step flavor is reachable by name, correct
    at every depth, matvec and batched."""
    a = rng.standard_normal((64, 64))
    x = rng.standard_normal(64)
    b = rng.standard_normal((64, 3))
    mesh = make_mesh(8)
    strat = get_strategy("colwise")
    y = strat.build(mesh, combine="overlap_ring", stages=stages)(
        jnp.asarray(a), jnp.asarray(x)
    )
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-10)
    c = strat.build_batched(mesh, combine="overlap_ring", stages=stages)(
        jnp.asarray(a), jnp.asarray(b)
    )
    np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-10)
    assert "overlap_ring" in strat.combine_candidates(mesh)
    # The ring-step flavor is colwise-only (the gather family's overlap
    # already rides ring hops).
    assert not get_strategy("rowwise").supports_combine("overlap_ring")


def test_explicit_stages_reaches_bound_combine(devices, rng, monkeypatch):
    """Regression: build(stages=N) on an instance whose overlap combine
    comes from the BINDING (colwise_overlap registry entry), not the
    combine= argument, must run at N — not silently at the tuned/default
    stage count."""
    import matvec_mpi_multiplier_tpu.parallel.ring as ring

    a = rng.standard_normal((64, 64))
    x = rng.standard_normal(64)
    mesh = make_mesh(8)
    calls = []
    real = ring.staged_overlap_scatter

    def spy(ap, xs, axes, kernel, stages, step="psum_scatter"):
        calls.append(stages)
        return real(ap, xs, axes, kernel, stages, step)

    monkeypatch.setattr(ring, "staged_overlap_scatter", spy)
    y = get_strategy("colwise_overlap").build(mesh, stages=8)(
        jnp.asarray(a), jnp.asarray(x)
    )
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-10)
    assert calls == [8]


def test_stage_clamping(devices, rng):
    """A requested S that doesn't divide the per-device chunk clamps DOWN
    the ladder instead of crashing a shape validate() accepts."""
    mesh = make_mesh(8)
    strat = get_strategy("colwise")
    # m=48, p=8: chunk 6 — ladder [2, 1]; S=8 clamps to 2.
    assert strat.resolve_stages(48, 32, mesh, 8, 8, "float32") == 2
    assert strat.resolve_stages(48, 32, mesh, 1, 8, "float32") == 1
    assert strat.resolve_stages(64, 32, mesh, 8, 8, "float32") == 8
    a = rng.standard_normal((48, 32))
    x = rng.standard_normal(32)
    y = strat.build(mesh, combine="overlap", stages=8)(
        jnp.asarray(a), jnp.asarray(x)
    )
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-10)
    with pytest.raises(ValueError, match="stages"):
        strat.resolve_stages(64, 32, mesh, 0, 8, "float32")
    with pytest.raises(ShardingError):
        strat.resolve_stages(60, 32, mesh, 2, 8, "float32")


def test_stages_default_on_cache_miss(devices, cache_path):
    mesh = make_mesh(8)
    s = get_strategy("colwise").resolve_stages(
        64, 64, mesh, None, 8, "float32"
    )
    assert s == DEFAULT_OVERLAP_STAGES


# ------------------------------------------------------------- batched


@pytest.mark.parametrize("stages", [1, 2, 4])
def test_overlap_batched_colwise(devices, rng, stages):
    mesh = make_mesh(8)
    a = rng.standard_normal((64, 64))
    b = rng.standard_normal((64, 6))
    c = get_strategy("colwise").build_batched(
        mesh, combine="overlap", stages=stages
    )(jnp.asarray(a), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-10)


def test_build_gemm_overlap(devices, rng):
    mesh = make_mesh(8)
    a = rng.standard_normal((64, 64)).astype(np.float32)
    b = rng.standard_normal((64, 8)).astype(np.float32)
    c = build_gemm("colwise_overlap", mesh, stages=2)(a, b)
    np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-4)
    c = build_gemm("colwise", mesh, combine="overlap", stages=4)(a, b)
    np.testing.assert_allclose(np.asarray(c), a @ b, rtol=1e-4)


def test_overlap_gather_family_is_matvec_only(devices):
    """rowwise/blockwise batched overlap has no in-body face — the batched
    output gather is XLA's to schedule (same contract as 'ring')."""
    mesh = make_mesh(8)
    with pytest.raises(ValueError, match="batched combine"):
        get_strategy("rowwise").build_batched(mesh, combine="overlap")
    assert not get_strategy("rowwise").supports_combine_batched("overlap")
    assert get_strategy("colwise").supports_combine_batched("overlap")


# -------------------------------------------------------- auto + tuner


def test_supports_combine_overlap_predicates(devices):
    for name in OVERLAP_STRATEGIES:
        assert get_strategy(name).supports_combine("overlap"), name
    mesh = make_mesh(8)
    for name in OVERLAP_STRATEGIES:
        assert "overlap" in get_strategy(name).combine_candidates(mesh), name


def test_combine_auto_dispatches_overlap_winner(
    devices, rng, cache_path, monkeypatch
):
    """A recorded 'overlap' combine winner routes auto dispatch through the
    staged scatter, at the stage count the overlap axis recorded."""
    import matvec_mpi_multiplier_tpu.parallel.ring as ring

    a = rng.uniform(0, 10, (64, 64)).astype(np.float32)
    x = rng.uniform(0, 10, (64,)).astype(np.float32)
    mesh = make_mesh(8)
    cache = TuningCache.load(cache_path)
    cache.record(
        combine_key("matvec", "colwise", 64, 64, 8, "float32"),
        {"combine": "overlap"},
    )
    cache.record(
        overlap_key("colwise", 64, 64, 8, "float32"),
        {"stages": 4},
    )
    cache.save()
    reset_cache()
    assert lookup_overlap(
        strategy="colwise", m=64, k=64, p=8, dtype="float32"
    ) == {"stages": 4}

    calls = []
    real = ring.staged_overlap_scatter

    def spy(ap, xs, axes, kernel, stages, step="psum_scatter"):
        calls.append(stages)
        return real(ap, xs, axes, kernel, stages, step)

    monkeypatch.setattr(ring, "staged_overlap_scatter", spy)
    y = get_strategy("colwise").build(mesh, combine="auto")(a, x)
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-4)
    assert calls == [4], "auto winner did not route through staged scatter"


def test_tune_overlap_smoke(devices, cache_path):
    """One real (tiny) stage-axis pass: the whole valid ladder is measured,
    the winner recorded, and resolve_stages then serves it."""
    from matvec_mpi_multiplier_tpu.tuning.search import tune_overlap

    mesh = make_mesh(4)
    cache = TuningCache.load(cache_path)
    decision = tune_overlap(
        "colwise", mesh, 64, 64, "float32", cache,
        measure="sync", n_reps=2, samples=1, log=lambda *_: None,
    )
    assert decision is not None
    assert decision["stages"] in (1, 2, 4, 8)
    assert set(decision["candidates"]) == {"1", "2", "4", "8"}
    cache.save()
    reset_cache()
    assert lookup_overlap(
        strategy="colwise", m=64, k=64, p=4, dtype="float32"
    ) == decision
    # Dispatch-side resolution serves the measured winner.
    assert get_strategy("colwise").resolve_stages(
        64, 64, mesh, None, 4, "float32"
    ) == decision["stages"]
    # Cache hit never re-measures.
    again = tune_overlap(
        "colwise", mesh, 64, 64, "float32", cache,
        measure="sync", n_reps=2, samples=1,
        log=lambda *_: pytest.fail("cache hit must not re-measure"),
    )
    assert again == decision
    # A shape no overlap schedule accepts records nothing.
    assert tune_overlap(
        "colwise", mesh, 63, 64, "float32", cache,
        measure="sync", n_reps=2, samples=1, log=lambda *_: None,
    ) is None


def test_cache_v2_file_still_loads(cache_path):
    """Schema v3 bump compatibility: v2 files (pre-overlap entries) keep
    serving their decisions instead of forcing a silent full re-tune."""
    from matvec_mpi_multiplier_tpu.tuning import gemv_key

    key = gemv_key(8, 8, "float32")
    cache_path.write_text(json.dumps({
        "version": 2, "entries": {key: {"kernel": "xla"}},
    }))
    assert TuningCache.load(cache_path).lookup(key) == {"kernel": "xla"}


# --------------------------------------------------------------- engine


def test_engine_overlap_combine(devices, rng, cache_path):
    """The engine pins S at construction and bakes it into the executable
    keys, so the AOT cache distinguishes stage counts."""
    a = rng.uniform(0, 10, (64, 64)).astype(np.float32)
    mesh = make_mesh(8)
    eng = MatvecEngine(
        a, mesh, strategy="colwise", combine="overlap", stages=4, promote=2,
        max_bucket=8,
    )
    assert eng.stages == 4
    assert eng._matvec_key_locked().combine == "overlap@4"
    assert eng._gemm_key_locked(8).combine == "overlap@4"
    x = rng.uniform(0, 10, (64,)).astype(np.float32)
    np.testing.assert_allclose(eng(x), a @ x, rtol=1e-4)
    blk = rng.uniform(0, 10, (64, 5)).astype(np.float32)
    np.testing.assert_allclose(eng(blk), a @ blk, rtol=1e-4)
    # Zero steady-state compiles holds for the staged schedules too.
    eng.warmup()
    baseline = eng.stats.compiles
    for w in (1, 3, 5, 8, 2):
        eng.submit(blk[:, :w]).result()
    assert eng.stats.compiles == baseline


def test_engine_overlap_stages_auto_from_cache(devices, rng, cache_path):
    cache = TuningCache.load(cache_path)
    cache.record(overlap_key("colwise", 64, 64, 8, "float32"), {"stages": 8})
    cache.save()
    reset_cache()
    a = rng.uniform(0, 10, (64, 64)).astype(np.float32)
    eng = MatvecEngine(
        a, make_mesh(8), strategy="colwise", combine="overlap", promote=None,
    )
    assert eng.stages == 8
    # Non-overlap engines resolve no stage count at all.
    eng2 = MatvecEngine(a, make_mesh(8), strategy="colwise", promote=None)
    assert eng2.stages is None


def test_engine_strategy_bound_overlap_resolves_stages(devices, rng):
    """Regression: an engine built on the colwise_overlap registry entry
    (combine=None — the schedule comes from the strategy binding) must
    still pin S and label its executables with it."""
    a = rng.uniform(0, 10, (64, 64)).astype(np.float32)
    eng = MatvecEngine(
        a, make_mesh(8), strategy="colwise_overlap", stages=4, promote=2,
        max_bucket=8,
    )
    assert eng.stages == 4
    assert eng._matvec_key_locked().combine == "overlap@4"
    x = rng.uniform(0, 10, (64,)).astype(np.float32)
    np.testing.assert_allclose(eng(x), a @ x, rtol=1e-4)
    blk = rng.uniform(0, 10, (64, 5)).astype(np.float32)
    np.testing.assert_allclose(eng(blk), a @ blk, rtol=1e-4)


# ---------------------------------------------------- pallas collective


@pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
def test_pallas_collective_ring_gemv(devices, rng, n_dev):
    from matvec_mpi_multiplier_tpu.ops.pallas_collective import (
        collective_ring_gemv,
    )

    mesh = make_1d_mesh(n_dev, axis_name="d")
    a = rng.standard_normal((64, 32))
    x = rng.standard_normal(32)
    y = jax.jit(shard_map(
        lambda ap, xs: collective_ring_gemv(ap, xs, "d"),
        mesh=mesh, in_specs=(P(None, "d"), P("d")), out_specs=P("d"),
        check_vma=False,
    ))(a, x)
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-12)


def test_pallas_ring_combine_through_build(devices, rng):
    a = rng.standard_normal((64, 64))
    x = rng.standard_normal(64)
    mesh = make_1d_mesh(8)
    y = get_strategy("colwise").build(mesh, combine="pallas_ring")(
        jnp.asarray(a), jnp.asarray(x)
    )
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-12)


def test_pallas_ring_fp32(devices, rng):
    a = rng.standard_normal((32, 32)).astype(np.float32)
    x = rng.standard_normal(32).astype(np.float32)
    mesh = make_1d_mesh(4)
    y = get_strategy("colwise").build(mesh, combine="pallas_ring")(
        jnp.asarray(a), jnp.asarray(x)
    )
    assert y.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(y), a @ x, rtol=1e-5)


def test_pallas_ring_needs_1d_mesh(devices, rng):
    """Multi-axis meshes have no single-link neighbor ring: rejected at the
    validate layer (ShardingError, skippable by the sweep driver)."""
    a = rng.standard_normal((64, 64))
    x = rng.standard_normal(64)
    mesh = make_mesh(8)  # 2x4: two named axes
    strat = get_strategy("colwise", combine="pallas_ring")
    with pytest.raises(ShardingError, match="single-axis"):
        strat.validate(64, 64, mesh)
    with pytest.raises(ShardingError, match="single-axis"):
        strat.build(mesh)(jnp.asarray(a), jnp.asarray(x))


def test_pallas_ring_is_matvec_only(devices):
    mesh = make_1d_mesh(8)
    with pytest.raises(ValueError, match="batched combine"):
        get_strategy("colwise").build_batched(mesh, combine="pallas_ring")
    assert not get_strategy("colwise").supports_combine_batched("pallas_ring")


def test_pallas_ring_candidate_gating(devices, monkeypatch):
    """Offered to the tuner only where the tile ladders are: single-axis
    mesh AND (TPU or the interpret ladder forced in)."""
    strat = get_strategy("colwise")
    mesh_1d, mesh_2d = make_1d_mesh(8), make_mesh(8)
    monkeypatch.delenv("MATVEC_TUNE_PALLAS", raising=False)
    assert "pallas_ring" not in strat.combine_candidates(mesh_1d)
    monkeypatch.setenv("MATVEC_TUNE_PALLAS", "1")
    assert "pallas_ring" in strat.combine_candidates(mesh_1d)
    assert "pallas_ring" not in strat.combine_candidates(mesh_2d)
    # Never a batched candidate, gating aside.
    assert "pallas_ring" not in strat.combine_candidates_batched(mesh_1d)

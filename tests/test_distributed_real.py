"""Real 2-process ``jax.distributed`` run on localhost CPU.

Round-2 review finding: the multi-host semantics — ``_max_across_processes``
(the ``MPI_Reduce(MPI_MAX)`` analog, ``src/multiplier_rowwise.c:147``) and
``append_result``'s coordinator-only CSV guard (the reference's
``rank == MAIN_PROCESS`` block, ``src/multiplier_rowwise.c:159-170``) — were
pinned only behind monkeypatched ``jax.process_count``. This test launches two
actual processes joined by ``jax.distributed.initialize`` and asserts the real
wiring: the true max crosses processes and exactly one process writes the CSV.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

WORKER = """
import json, os, sys

idx = int(sys.argv[1])
port = sys.argv[2]
root = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = ""  # no inherited virtual-device forcing

import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=idx
)

from matvec_mpi_multiplier_tpu.bench import metrics
from matvec_mpi_multiplier_tpu.bench.timing import (
    TimingResult,
    _max_across_processes,
)

# Distinct per-process elapsed times: the reduce must pick process 1's.
local_elapsed = 1.5 if idx == 0 else 3.5
global_elapsed = _max_across_processes(local_elapsed)

result = TimingResult(
    n_rows=4, n_cols=8, n_devices=jax.device_count(), strategy="rowwise",
    dtype="float64", mode="amortized", measure="sync",
    mean_time_s=global_elapsed, times_s=(global_elapsed,), n_reps=1,
)
path = metrics.append_result(result, root)
print(json.dumps({
    "idx": idx,
    "process_count": jax.process_count(),
    "global_elapsed": global_elapsed,
    "csv": str(path),
}))
"""


MATVEC_WORKER = """
import json, os, sys

idx = int(sys.argv[1])
port = sys.argv[2]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = ""  # 1 local CPU device per process -> 2 global

import jax
import numpy as np

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=idx
)
assert jax.device_count() == 2 and jax.local_device_count() == 1

from jax.sharding import NamedSharding

from matvec_mpi_multiplier_tpu import get_strategy, make_mesh

# Both processes build the same global mesh and run the same SPMD program —
# the reference's mpiexec shape, with one JAX process per "host".
mesh = make_mesh(2)
strat = get_strategy("rowwise")
rng = np.random.default_rng(5)  # same seed everywhere: same global operands
a = rng.standard_normal((16, 8))
x = rng.standard_normal(8)
strat.validate(16, 8, mesh)

sh_a, sh_x = strat.shardings(mesh)
ga = jax.make_array_from_callback(a.shape, sh_a, lambda i: a[i])
gx = jax.make_array_from_callback(x.shape, sh_x, lambda i: x[i])
y = strat.build(mesh)(ga, gx)  # gather_output=True: replicated result
err = float(np.max(np.abs(np.asarray(y) - a @ x)))
print(json.dumps({"idx": idx, "err": err, "n_dev": jax.device_count()}))
"""


LOOP_WORKER = """
import json, os, sys

idx = int(sys.argv[1])
port = sys.argv[2]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = ""  # 1 local CPU device per process -> 2 global

import jax
import numpy as np

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=idx
)

from matvec_mpi_multiplier_tpu import get_strategy, make_mesh
from matvec_mpi_multiplier_tpu.bench.timing import benchmark_strategy

mesh = make_mesh(2)
strat = get_strategy("rowwise")
rng = np.random.default_rng(7)  # same seed everywhere: same global operands
a = rng.standard_normal((32, 16))
x = rng.standard_normal(16)
res = benchmark_strategy(
    strat, mesh, a, x, dtype="float64", n_reps=4, measure="loop",
    chain_samples=2,
)
print(json.dumps({
    "idx": idx, "times": list(res.times_s), "mean": res.mean_time_s,
}))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(tmp_path, worker_src: str, *extra_argv: str) -> dict:
    """Launch two coordinated worker processes and return their JSON outputs
    keyed by process index. Asserts both exit 0."""
    port = _free_port()
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(worker_src)
    env = dict(os.environ, PYTHONPATH=str(REPO))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker_py), str(i), str(port), *extra_argv],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            if p.returncode != 0 and (
                "Multiprocess computations aren't implemented" in err
            ):
                # Old jaxlib CPU backends (e.g. 0.4.x here) have no cross-
                # process CPU collectives at all — an install capability
                # gap, not a defect in the SPMD programs under test.
                pytest.skip(
                    "this jaxlib's CPU backend has no multiprocess support"
                )
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return {o["idx"]: o for o in outs}


def test_two_process_distributed_matvec(tmp_path):
    """A real cross-process sharded matvec: two jax.distributed processes,
    one device each, one global mesh, the rowwise strategy's actual SPMD
    program — the reference's multi-rank execution model
    (``mpiexec -n p``, ``test.sh:11``) run for real, not behind fakes."""
    by_idx = _run_workers(tmp_path, MATVEC_WORKER)
    for o in by_idx.values():
        assert o["n_dev"] == 2
        assert o["err"] < 1e-12  # fp64 exactness vs the local numpy oracle


def test_two_process_loop_measure_lockstep(tmp_path):
    """The device-looped slope measure across two REAL jax.distributed
    processes. Every probe time inside ``_loop_slope`` is max-reduced at the
    source, so both processes make identical spread-growth and TimingError
    decisions — divergent control flow would dispatch different numbers of
    the sharded program and deadlock (caught here by the subprocess
    timeout). Identical per-sample estimates on both sides prove the
    lockstep held end-to-end."""
    by_idx = _run_workers(tmp_path, LOOP_WORKER)
    assert by_idx[0]["times"] == by_idx[1]["times"]
    assert by_idx[0]["mean"] == by_idx[1]["mean"]
    assert by_idx[0]["mean"] > 0


def test_two_process_max_reduce_and_coordinator_csv(tmp_path):
    by_idx = _run_workers(tmp_path, WORKER, str(tmp_path))
    assert by_idx[0]["process_count"] == 2
    # Both processes must agree on the true (cross-process) max, not their
    # local value — process 0's local 1.5 must have been replaced by 3.5.
    assert by_idx[0]["global_elapsed"] == 3.5
    assert by_idx[1]["global_elapsed"] == 3.5

    # Exactly one row: only the coordinator appended (both called
    # append_result with the same root).
    csv = tmp_path / "out" / "rowwise.csv"
    lines = csv.read_text().strip().splitlines()
    assert lines[0] == "n_rows, n_cols, n_processes, time"
    assert len(lines) == 2, f"expected 1 data row, got {lines[1:]}"
    assert lines[1].startswith("4, 8, ")
    ext = (tmp_path / "out" / "results_extended.csv").read_text().strip()
    assert len(ext.splitlines()) == 2


RING_WORKER = """
import json, os, sys

idx = int(sys.argv[1])
port = sys.argv[2]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = ""  # 1 local CPU device per process -> 2 global

import jax
import numpy as np

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=idx
)
# One local device per process, or the ring never crosses a process
# boundary and the test silently stops testing cross-host ppermute.
assert jax.device_count() == 2 and jax.local_device_count() == 1

from matvec_mpi_multiplier_tpu import get_strategy, make_mesh

# The explicit neighbor-ring paths with ppermute hops that REALLY cross a
# process boundary: the colwise_ring combine (reduce-scatter) composed with
# the ring all-gather (gather_output="ring") — end-to-end the only
# collectives in the program are ppermutes.
mesh = make_mesh(2)
strat = get_strategy("colwise_ring")
rng = np.random.default_rng(9)  # same seed everywhere: same global operands
a = rng.standard_normal((16, 8))
x = rng.standard_normal(8)
strat.validate(16, 8, mesh)

sh_a, sh_x = strat.shardings(mesh)
ga = jax.make_array_from_callback(a.shape, sh_a, lambda i: a[i])
gx = jax.make_array_from_callback(x.shape, sh_x, lambda i: x[i])
y = strat.build(mesh, gather_output="ring")(ga, gx)
replicated = y.sharding.is_fully_replicated
err = float(np.max(np.abs(np.asarray(y) - a @ x)))
print(json.dumps({"idx": idx, "err": err, "replicated": bool(replicated)}))
"""


def test_two_process_ring_collectives(tmp_path):
    """ppermute neighbor rings across a REAL process boundary: the
    colwise_ring reduce-scatter plus the ring all-gather
    (gather_output="ring") — the long-context/sequence-parallel primitive
    family (SURVEY.md 5.7) exercised cross-host, not just on a virtual
    single-process mesh."""
    by_idx = _run_workers(tmp_path, RING_WORKER)
    for o in by_idx.values():
        assert o["replicated"] is True
        assert o["err"] < 1e-12


ATTENTION_WORKER = """
import json, os, sys

idx = int(sys.argv[1])
port = sys.argv[2]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = ""  # 1 local CPU device per process -> 2 global

import jax
import numpy as np

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=idx
)
assert jax.device_count() == 2 and jax.local_device_count() == 1

from matvec_mpi_multiplier_tpu.parallel.attention import (
    build_ring_attention,
    build_ulysses_attention,
)
from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh

mesh = make_mesh(2)
s, h, dh = 32, 2, 8
rng = np.random.default_rng(13)  # same seed everywhere: same global operands
q = rng.standard_normal((s, h, dh)).astype(np.float32)
k = rng.standard_normal((s, h, dh)).astype(np.float32)
v = rng.standard_normal((s, h, dh)).astype(np.float32)

# Dense causal oracle, computed locally on each process.
sc = np.einsum("qhd,khd->hqk", q, k) / np.sqrt(dh)
r = np.arange(s)
sc = np.where((r[None, :] <= r[:, None])[None], sc, -np.inf)
w = np.exp(sc - sc.max(-1, keepdims=True))
oracle = np.einsum("hqk,khd->qhd", w / w.sum(-1, keepdims=True), v)

import jax.numpy as jnp

errs = {}
for name, build in (("ring", build_ring_attention),
                    ("ulysses", build_ulysses_attention)):
    attn = build(mesh, causal=True, gather_output=True)
    o = np.asarray(attn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    errs[name] = float(np.max(np.abs(o - oracle)))
print(json.dumps({"idx": idx, **errs}))
"""


def test_two_process_attention_schedules(tmp_path):
    """Both long-context operators across a REAL process boundary: ring
    attention's KV ppermute hops and Ulysses' all_to_all exchanges each
    cross jax.distributed processes (one device per process), and both
    match the dense causal oracle — the sequence-parallel operators
    themselves exercised cross-host, beyond the primitive-level ring test
    above."""
    by_idx = _run_workers(tmp_path, ATTENTION_WORKER)
    for o in by_idx.values():
        assert o["ring"] < 5e-6
        assert o["ulysses"] < 5e-6


FLASH_WORKER = """
import json, os, sys

idx = int(sys.argv[1])
port = sys.argv[2]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = ""  # 1 local CPU device per process -> 2 global

import jax
import numpy as np

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=idx
)
assert jax.device_count() == 2 and jax.local_device_count() == 1

from matvec_mpi_multiplier_tpu.parallel.attention import build_ring_attention
from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh

# s=256 on p=2 gives (128, 128) per-hop blocks at d_head=128 — shapes the
# Pallas tile ACCEPTS (flash_path_available), so the fused tier itself
# (interpret mode) runs across the process boundary, not its fallback.
# Single head keeps per-device interpret work far below the CPU
# collective-rendezvous termination timeout.
mesh = make_mesh(2)
s, d = 256, 128
rng = np.random.default_rng(17)
q = rng.standard_normal((s, d)).astype(np.float32)
k = rng.standard_normal((s, d)).astype(np.float32)
v = rng.standard_normal((s, d)).astype(np.float32)

import jax.numpy as jnp

o_xla = np.asarray(build_ring_attention(mesh, causal=True, gather_output=True)(
    jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
o_flash = np.asarray(build_ring_attention(
    mesh, causal=True, gather_output=True, kernel="flash")(
    jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
err = float(np.max(np.abs(o_flash - o_xla)))
print(json.dumps({"idx": idx, "err": err}))
"""


def test_two_process_flash_tier(tmp_path):
    """The fused Pallas tile inside the ring, executed across a REAL
    process boundary at shapes the kernel accepts (not its fallback):
    cross-process ppermute hops feeding interpret-mode pallas_call, flash
    agreeing with the xla tier on both processes."""
    by_idx = _run_workers(tmp_path, FLASH_WORKER)
    for o in by_idx.values():
        assert o["err"] < 5e-6

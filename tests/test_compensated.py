"""Compensated (double-float) GEMV kernel: fp64-grade accumulation in fp32.

The reference computes in C double (src/matr_utils.c:86-96); TPU has no fp64.
These tests pin the SURVEY.md §7 hard-part-(ii) answer: the "compensated"
kernel must track fp64 ground truth to ~1 ulp of fp32 where plain fp32
accumulation drifts or collapses.
"""

import numpy as np
import pytest

from matvec_mpi_multiplier_tpu import get_strategy, make_mesh
from matvec_mpi_multiplier_tpu.ops.compensated import (
    gemv_compensated,
    two_prod,
    two_sum,
)
from matvec_mpi_multiplier_tpu.ops.gemv import available_kernels, gemv_xla

import jax.numpy as jnp


def _ulps(y, truth):
    """Error in units of fp32 ulp of the true value."""
    t32 = truth.astype(np.float32)
    return np.abs(y.astype(np.float64) - truth) / np.spacing(np.abs(t32))


def test_registered():
    assert "compensated" in available_kernels()


def test_two_sum_exact():
    a = jnp.float32(1e8)
    b = jnp.float32(1.0)
    s, e = two_sum(a, b)
    # 1e8 + 1 is not representable in fp32; the error term recovers it.
    assert float(s) + float(e) == 100000001.0


def test_two_prod_exact():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.uniform(-10, 10, 1024), jnp.float32)
    b = jnp.asarray(rng.uniform(-10, 10, 1024), jnp.float32)
    p, e = two_prod(a, b)
    exact = np.asarray(a, np.float64) * np.asarray(b, np.float64)
    np.testing.assert_array_equal(
        np.asarray(p, np.float64) + np.asarray(e, np.float64), exact
    )


def test_long_contraction_beats_plain_fp32(devices):
    # Uniform positive data, long k: plain fp32 random-walks away from the
    # fp64 truth; compensated stays within ~1 ulp.
    rng = np.random.default_rng(1)
    m, k = 8, 1 << 16
    a64 = rng.uniform(0.0, 10.0, (m, k))
    x64 = rng.uniform(0.0, 10.0, k)
    truth = a64 @ x64
    a32 = jnp.asarray(a64, jnp.float32)
    x32 = jnp.asarray(x64, jnp.float32)

    plain = np.asarray(gemv_xla(a32, x32))
    comp = np.asarray(gemv_compensated(a32, x32))
    assert _ulps(comp, truth).max() <= 2.0
    # ... and is at least 10x closer than the plain kernel on this regime.
    assert _ulps(comp, truth).max() * 10 < _ulps(plain, truth).max()


def test_catastrophic_cancellation(devices):
    # Rows of (big, 1, -big) triples: the true result is the count of small
    # terms; plain fp32 accumulation returns garbage scaled by `big`.
    m, triples = 4, 256
    k = 3 * triples
    a = np.zeros((m, k), np.float32)
    a[:, 0::3] = 3e7
    a[:, 1::3] = 1.0
    a[:, 2::3] = -3e7
    x = np.ones(k, np.float32)
    truth = np.full(m, float(triples))
    comp = np.asarray(gemv_compensated(jnp.asarray(a), jnp.asarray(x)))
    np.testing.assert_array_equal(comp, truth.astype(np.float32))


@pytest.mark.parametrize("name", ["rowwise", "colwise", "blockwise"])
def test_strategies_with_compensated_kernel(devices, name):
    # The kernel plugs into every strategy; distributed result tracks the
    # fp64 oracle to fp32 ulp despite fp32 storage.
    rng = np.random.default_rng(2)
    m, k = 64, 512
    a64 = rng.uniform(0.0, 10.0, (m, k))
    x64 = rng.uniform(0.0, 10.0, k)
    mesh = make_mesh(8)
    fn = get_strategy(name).build(mesh, kernel="compensated")
    y = np.asarray(fn(jnp.asarray(a64, jnp.float32), jnp.asarray(x64, jnp.float32)))
    assert _ulps(y, a64 @ x64).max() <= 4.0


def test_fp64_inputs_run_quad(devices):
    # fp64 inputs run the same EFT algorithm in fp64 pairs on CPU.
    rng = np.random.default_rng(3)
    a = rng.uniform(0.0, 10.0, (8, 128))
    x = rng.uniform(0.0, 10.0, 128)
    y = np.asarray(gemv_compensated(jnp.asarray(a), jnp.asarray(x)))
    np.testing.assert_allclose(y, a @ x, rtol=1e-15)


def test_huge_magnitudes_degrade_not_nan(devices):
    # |a| beyond the Dekker-split overflow threshold (~8.3e34 in fp32) must
    # degrade to plain-product accuracy, never NaN.
    a = jnp.asarray([[1e35, 2.0]], jnp.float32)
    x = jnp.asarray([1e-30, 3.0], jnp.float32)
    y = np.asarray(gemv_compensated(a, x))
    np.testing.assert_allclose(y, [1e5 + 6.0], rtol=1e-6)


def test_empty_contraction(devices):
    y = np.asarray(gemv_compensated(jnp.zeros((4, 0)), jnp.zeros((0,))))
    np.testing.assert_array_equal(y, np.zeros(4))

"""In-suite adapter over the staticcheck AST rule engine.

The rule catalogue itself lives in ``matvec_mpi_multiplier_tpu/staticcheck``
(one engine, shared with the ``scripts/tier1.sh`` fail-fast gate — the
duplicated grep bodies both entry points used to carry are gone). This
module only asserts the two repo-level verdicts the tier-1 suite owns:

* the checked-in tree is clean under the full rule catalogue;
* every exemption marker in the registry carries a reason — parameterized
  over :data:`MARKERS`, so registering a new rule with a marker grows this
  test automatically (it cannot be forgotten).

Per-rule behavior (known-bad fixtures, alias resolution, string/docstring
immunity, CLI/API agreement) is covered by ``tests/test_staticcheck.py``;
the lowered-HLO schedule audit rides there too.
"""

import pytest

from matvec_mpi_multiplier_tpu.staticcheck import (
    MARKERS,
    check_marker_reasons,
    render_text,
    run_rules,
)


def test_repo_clean_under_rule_catalogue():
    findings = run_rules()
    assert not findings, (
        "staticcheck rule violations in the checked-in tree:\n"
        + render_text(findings)
    )


@pytest.mark.parametrize("marker", sorted(MARKERS))
def test_markers_carry_reasons(marker):
    """The exemption marker is a justification, not an escape hatch: every
    `# <marker>: <reason>` in the marker's rule scope must be a comment
    with a non-empty reason."""
    bad = check_marker_reasons(marker)
    assert not bad, (
        f"'{marker}:' markers without a reason:\n" + render_text(bad)
    )

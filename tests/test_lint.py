"""Repo lint gates that must ride the tier-1 suite.

The JAX cross-version shim (``utils/compat.py``) only works if it is the
single chokepoint: one stray direct shard_map reference re-breaks every
test on an older install the moment that module is imported. The grep here
mirrors ``scripts/tier1.sh``'s fail-fast lint so the rule is enforced even
when the suite is invoked directly (the ROADMAP tier-1 command).
"""

import re
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SHIM = REPO / "matvec_mpi_multiplier_tpu" / "utils" / "compat.py"

_PATTERN = re.compile(
    r"jax\.shard_map"
    r"|jax\.experimental\.shard_map"
    r"|from jax\.experimental import shard_map"
)

_SCAN_ROOTS = ("matvec_mpi_multiplier_tpu", "tests", "scripts")
_SCAN_FILES = ("bench.py", "__graft_entry__.py")


def _python_sources():
    for root in _SCAN_ROOTS:
        yield from sorted((REPO / root).rglob("*.py"))
    for name in _SCAN_FILES:
        p = REPO / name
        if p.exists():
            yield p


def test_no_direct_shard_map_outside_compat():
    offenders = []
    for path in _python_sources():
        if path == SHIM:
            continue
        for lineno, line in enumerate(
            path.read_text().splitlines(), start=1
        ):
            if _PATTERN.search(line):
                offenders.append(f"{path.relative_to(REPO)}:{lineno}: {line.strip()}")
    assert not offenders, (
        "direct shard_map references outside utils/compat.py (route them "
        "through matvec_mpi_multiplier_tpu.utils.compat):\n"
        + "\n".join(offenders)
    )


# The serving engine's dispatch path must never host-sync: a single
# block_until_ready (or materializing np.asarray) in the hot loop turns the
# async submit contract into a per-request device round-trip. Timing/driver
# code (bench/serve.py) is exempt by living outside engine/; the engine's
# own deliberate sync points (future materialization, one-time host
# staging) carry a `sync-ok:` marker with a reason. Mirrored fail-fast in
# scripts/tier1.sh.
ENGINE = REPO / "matvec_mpi_multiplier_tpu" / "engine"

_SYNC_PATTERN = re.compile(
    r"block_until_ready|device_get|np\.asarray|np\.array\(|jnp\.asarray"
)
_SYNC_EXEMPT = "sync-ok:"


def test_no_host_syncs_in_engine_dispatch():
    offenders = []
    for path in sorted(ENGINE.rglob("*.py")):
        for lineno, line in enumerate(
            path.read_text().splitlines(), start=1
        ):
            if _SYNC_PATTERN.search(line) and _SYNC_EXEMPT not in line:
                offenders.append(
                    f"{path.relative_to(REPO)}:{lineno}: {line.strip()}"
                )
    assert not offenders, (
        "host syncs in engine/ dispatch paths (mark deliberate "
        "materialization points with `# sync-ok: <reason>`; timing code "
        "belongs in bench/serve.py):\n" + "\n".join(offenders)
    )


def test_engine_sync_markers_carry_reasons():
    """The exemption marker is a justification, not an escape hatch: every
    `sync-ok:` must be a comment with a non-empty reason."""
    bad = []
    for path in sorted(ENGINE.rglob("*.py")):
        for lineno, line in enumerate(
            path.read_text().splitlines(), start=1
        ):
            if _SYNC_EXEMPT in line:
                tail = line.split(_SYNC_EXEMPT, 1)[1].strip()
                if "#" not in line.split(_SYNC_EXEMPT)[0] or not tail:
                    bad.append(f"{path.relative_to(REPO)}:{lineno}")
    assert not bad, f"sync-ok markers without comment+reason: {bad}"


# The staged overlap schedules exist to hide communication behind compute:
# a full-width `jax.lax.all_gather(...)` / `jax.lax.psum(...)` inside an
# overlap schedule body would re-serialize exactly the transfer the S-stage
# pipeline chunks — the schedule would measure like the un-staged baseline
# while claiming to overlap. Deliberate chunked uses (the per-stage psum
# over blockwise's grid columns, 1/S of the rows per issue) carry an
# `# overlap-ok: <reason>` marker. Mirrored fail-fast in scripts/tier1.sh.
OVERLAP_BODIES = (
    REPO / "matvec_mpi_multiplier_tpu" / "parallel" / "ring.py",
    REPO / "matvec_mpi_multiplier_tpu" / "ops" / "pallas_collective.py",
)

_UNCHUNKED_PATTERN = re.compile(r"jax\.lax\.all_gather\(|jax\.lax\.psum\(")
_OVERLAP_EXEMPT = "overlap-ok:"


def test_no_unchunked_collectives_in_overlap_bodies():
    offenders = []
    for path in OVERLAP_BODIES:
        for lineno, line in enumerate(
            path.read_text().splitlines(), start=1
        ):
            if _UNCHUNKED_PATTERN.search(line) and _OVERLAP_EXEMPT not in line:
                offenders.append(
                    f"{path.relative_to(REPO)}:{lineno}: {line.strip()}"
                )
    assert not offenders, (
        "un-chunked full-width collectives in overlap schedule bodies "
        "(stage the collective, or mark a deliberate chunked use with "
        "`# overlap-ok: <reason>`):\n" + "\n".join(offenders)
    )


def test_overlap_markers_carry_reasons():
    """Same contract as the sync-ok marker: a justification, not an escape
    hatch."""
    bad = []
    for path in OVERLAP_BODIES:
        for lineno, line in enumerate(
            path.read_text().splitlines(), start=1
        ):
            if _OVERLAP_EXEMPT in line:
                tail = line.split(_OVERLAP_EXEMPT, 1)[1].strip()
                if "#" not in line.split(_OVERLAP_EXEMPT)[0] or not tail:
                    bad.append(f"{path.relative_to(REPO)}:{lineno}")
    assert not bad, f"overlap-ok markers without comment+reason: {bad}"


# The engine dispatch hot path (engine/ plus the obs in-memory layer) must
# never block on file I/O: a file write or json.dump inside submit would
# stall every request behind the filesystem — the whole reason the trace
# sink is a separate thread (obs/sink.py, the ONE exempt file besides the
# obs CLI, which is driver code). Deliberate non-hot-path writes elsewhere
# carry an `# obs-ok: <reason>` marker. Mirrored fail-fast in
# scripts/tier1.sh.
OBS = REPO / "matvec_mpi_multiplier_tpu" / "obs"
_IO_EXEMPT_FILES = (OBS / "sink.py", OBS / "__main__.py")

_IO_PATTERN = re.compile(
    r"\bopen\(|json\.dump|\.write\(|write_text\(|write_bytes\("
)
_IO_EXEMPT = "obs-ok:"


def _hot_path_sources():
    yield from sorted(ENGINE.rglob("*.py"))
    for path in sorted(OBS.rglob("*.py")):
        if path not in _IO_EXEMPT_FILES:
            yield path


def test_no_blocking_io_on_dispatch_hot_path():
    offenders = []
    for path in _hot_path_sources():
        for lineno, line in enumerate(
            path.read_text().splitlines(), start=1
        ):
            if _IO_PATTERN.search(line) and _IO_EXEMPT not in line:
                offenders.append(
                    f"{path.relative_to(REPO)}:{lineno}: {line.strip()}"
                )
    assert not offenders, (
        "blocking I/O on the engine dispatch hot path (route file writes "
        "through the obs sink thread, obs/sink.py, or mark a deliberate "
        "non-hot-path write with `# obs-ok: <reason>`):\n"
        + "\n".join(offenders)
    )


def test_obs_markers_carry_reasons():
    """Same contract as the sync-ok/overlap-ok markers: a justification,
    not an escape hatch."""
    bad = []
    for path in _hot_path_sources():
        for lineno, line in enumerate(
            path.read_text().splitlines(), start=1
        ):
            if _IO_EXEMPT in line:
                tail = line.split(_IO_EXEMPT, 1)[1].strip()
                if "#" not in line.split(_IO_EXEMPT)[0] or not tail:
                    bad.append(f"{path.relative_to(REPO)}:{lineno}")
    assert not bad, f"obs-ok markers without comment+reason: {bad}"

"""Repo lint gates that must ride the tier-1 suite.

The JAX cross-version shim (``utils/compat.py``) only works if it is the
single chokepoint: one stray direct shard_map reference re-breaks every
test on an older install the moment that module is imported. The grep here
mirrors ``scripts/tier1.sh``'s fail-fast lint so the rule is enforced even
when the suite is invoked directly (the ROADMAP tier-1 command).
"""

import re
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SHIM = REPO / "matvec_mpi_multiplier_tpu" / "utils" / "compat.py"

_PATTERN = re.compile(
    r"jax\.shard_map"
    r"|jax\.experimental\.shard_map"
    r"|from jax\.experimental import shard_map"
)

_SCAN_ROOTS = ("matvec_mpi_multiplier_tpu", "tests", "scripts")
_SCAN_FILES = ("bench.py", "__graft_entry__.py")


def _python_sources():
    for root in _SCAN_ROOTS:
        yield from sorted((REPO / root).rglob("*.py"))
    for name in _SCAN_FILES:
        p = REPO / name
        if p.exists():
            yield p


def test_no_direct_shard_map_outside_compat():
    offenders = []
    for path in _python_sources():
        if path == SHIM:
            continue
        for lineno, line in enumerate(
            path.read_text().splitlines(), start=1
        ):
            if _PATTERN.search(line):
                offenders.append(f"{path.relative_to(REPO)}:{lineno}: {line.strip()}")
    assert not offenders, (
        "direct shard_map references outside utils/compat.py (route them "
        "through matvec_mpi_multiplier_tpu.utils.compat):\n"
        + "\n".join(offenders)
    )

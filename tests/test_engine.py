"""Serving-engine tests (engine/): correctness across strategies/dtypes,
bucket-padding isolation, executable-cache behavior, and promotion policy.

Bitwise doctrine: below the promotion threshold the engine serves each
column through the SAME single-RHS executable a direct ``strategy.build``
call compiles, so those comparisons are exact. The promoted GEMM path runs
a genuinely different local kernel (a width-b matmul), whose backend
reduction order may differ from the width-1 case — there the contract is
tight allclose against the matvec loop, plus bitwise agreement with the
equivalent direct ``build_batched`` program (same executable shape).
"""

import numpy as np
import pytest

from matvec_mpi_multiplier_tpu import available_strategies, get_strategy, make_mesh
from matvec_mpi_multiplier_tpu.engine import (
    DEFAULT_PROMOTE_B,
    MatvecEngine,
    bucket_for,
    bucket_ladder,
    pad_columns,
    split_widths,
)
from matvec_mpi_multiplier_tpu.tuning import (
    TuningCache,
    combine_key,
    promote_key,
    reset_cache,
)
from matvec_mpi_multiplier_tpu.utils.errors import ConfigError

RTOL = {"float32": 1e-5, "float64": 1e-12}


@pytest.fixture()
def cache_path(tmp_path, monkeypatch):
    path = tmp_path / "tuning_cache.json"
    monkeypatch.setenv("MATVEC_TUNING_CACHE", str(path))
    reset_cache()
    yield path
    reset_cache()


def make_operands(rng, m=64, k=64, dtype="float32"):
    a = rng.uniform(0, 10, (m, k)).astype(dtype)
    X = rng.uniform(0, 10, (k, 11)).astype(dtype)
    return a, X


# ---------------------------------------------------------------- buckets


def test_bucket_ladder_and_quantization():
    assert bucket_ladder(16) == (1, 2, 4, 8, 16)
    assert bucket_ladder(24) == (1, 2, 4, 8, 16, 24)
    assert bucket_for(1, 16) == 1
    assert bucket_for(5, 16) == 8
    assert bucket_for(16, 16) == 16
    with pytest.raises(ConfigError):
        bucket_for(17, 16)
    with pytest.raises(ConfigError):
        bucket_for(0, 16)
    assert split_widths(40, 16) == [16, 16, 8]
    assert split_widths(16, 16) == [16]
    assert split_widths(3, 16) == [3]


def test_pad_columns_zero_fills():
    block = np.ones((4, 3), np.float32)
    padded = pad_columns(block, 8)
    assert padded.shape == (4, 8)
    np.testing.assert_array_equal(padded[:, :3], block)
    np.testing.assert_array_equal(padded[:, 3:], 0.0)
    assert pad_columns(block, 3) is block  # already at width: no copy
    with pytest.raises(ConfigError):
        pad_columns(block, 2)


# ----------------------------------------------------- correctness matrix


@pytest.mark.parametrize("strategy", available_strategies())
@pytest.mark.parametrize("dtype", ["float32", "float64"])
def test_engine_matches_single_matvec_loop(devices, rng, strategy, dtype):
    """Batched submits — sequential AND promoted — reproduce a loop of
    single matvecs for every strategy/dtype."""
    mesh = make_mesh(8)
    a, X = make_operands(rng, dtype=dtype)
    engine = MatvecEngine(
        a, mesh, strategy=strategy, promote=4, max_bucket=8
    )
    direct = get_strategy(strategy).build(mesh)
    loop = np.stack(
        [np.asarray(direct(a, X[:, j])) for j in range(X.shape[1])], axis=1
    )

    # Vector request: same executable class as the direct build — bitwise.
    y = engine.submit(X[:, 0]).result()
    np.testing.assert_array_equal(y, loop[:, 0])

    # Sub-threshold block (b=3 < b*=4): per-column path, bitwise.
    Y3 = engine.submit(X[:, :3]).result()
    assert Y3.shape == (64, 3)
    np.testing.assert_array_equal(Y3, loop[:, :3])

    # Promoted block (b=11 >= b*): padded GEMMs (8 + pad, 3 -> bucket 4).
    Y = engine.submit(X).result()
    assert Y.shape == loop.shape
    np.testing.assert_allclose(Y, loop, rtol=RTOL[dtype])


@pytest.mark.parametrize("dtype", ["float32", "float64"])
def test_promoted_block_bitwise_matches_build_batched(devices, rng, dtype):
    """The promoted path IS the strategy's batched program: same bucket
    shape in, bitwise-equal columns out."""
    mesh = make_mesh(8)
    a, X = make_operands(rng, dtype=dtype)
    block = X[:, :8]  # exactly one bucket: no padding in play
    engine = MatvecEngine(a, mesh, strategy="colwise", promote=2, max_bucket=8)
    got = engine.submit(block).result()
    want = np.asarray(
        get_strategy("colwise").build_batched(mesh)(a, block)
    )
    np.testing.assert_array_equal(got, want)


def test_bfloat16_batches(devices, rng):
    import jax.numpy as jnp

    mesh = make_mesh(8)
    a = rng.uniform(0, 1, (64, 64))
    X = rng.uniform(0, 1, (64, 6))
    engine = MatvecEngine(
        a, mesh, strategy="rowwise", dtype=jnp.bfloat16, promote=2
    )
    Y = engine.submit(X).result()
    assert Y.shape == (64, 6) and str(Y.dtype) == "bfloat16"
    np.testing.assert_allclose(
        Y.astype(np.float32),
        (a.astype(np.float32) @ X.astype(np.float32)), rtol=0.05,
    )


# ------------------------------------------------------- padding isolation


def test_bucket_padding_never_leaks(devices, rng):
    """A width-5 request rides the bucket-8 executable; its 5 result
    columns must be bitwise what the same executable computes for any
    other request sharing those columns, and the pad columns must never
    surface."""
    mesh = make_mesh(8)
    a, X = make_operands(rng)
    engine = MatvecEngine(a, mesh, strategy="rowwise", promote=2, max_bucket=8)
    X5, X8 = X[:, :5], X[:, :8]
    Y5 = engine.submit(X5).result()
    Y8 = engine.submit(X8).result()
    assert Y5.shape == (64, 5)
    np.testing.assert_array_equal(Y5, Y8[:, :5])
    # And the padded tail of the width-8 request is real data, not zeros.
    assert np.abs(Y8[:, 5:]).min() > 0


def test_split_request_spans_buckets(devices, rng):
    """A request wider than max_bucket splits into chunks, each padded to
    its own bucket, and reassembles in order."""
    mesh = make_mesh(8)
    a = rng.uniform(0, 10, (64, 64)).astype(np.float32)
    X = rng.uniform(0, 10, (64, 21)).astype(np.float32)  # 8 + 8 + 5->8
    engine = MatvecEngine(a, mesh, strategy="rowwise", promote=2, max_bucket=8)
    Y = engine.submit(X).result()
    assert Y.shape == (64, 21)
    np.testing.assert_allclose(Y, a @ X, rtol=1e-5)
    assert engine.n_executables == 1  # every chunk hit the bucket-8 program


# ------------------------------------------------- executable-cache state


def test_compile_count_flat_across_mixed_replay(devices, rng):
    """The acceptance criterion: after warmup covers the ladder, a
    mixed-shape request stream never compiles again — only cache hits."""
    mesh = make_mesh(8)
    a, X = make_operands(rng)
    engine = MatvecEngine(a, mesh, strategy="colwise", promote=2, max_bucket=8)
    warm_compiles = engine.warmup()
    # matvec + buckets {1, 2, 4, 8}
    assert warm_compiles == 1 + len(bucket_ladder(8))
    assert engine.warmup() == 0  # idempotent

    baseline = engine.stats.compiles
    futures = [
        engine.submit(X[:, :w]) for w in (1, 2, 3, 5, 8, 11, 7, 4, 6, 2)
    ]
    for f in futures:
        f.result()
    stats = engine.stats
    assert stats.compiles == baseline, "steady-state stream compiled"
    assert stats.hits > 0
    assert stats.requests == 10


def test_warmup_widths_subset(devices, rng):
    """warmup(widths) compiles exactly the buckets those widths hit."""
    mesh = make_mesh(8)
    a, _ = make_operands(rng)
    engine = MatvecEngine(a, mesh, strategy="rowwise", promote=2, max_bucket=16)
    n = engine.warmup(widths=[3, 4])  # both quantize to bucket 4
    assert n == 2  # matvec + bucket-4 gemm
    assert engine.n_executables == 2


def test_warmup_mirrors_submit_routing(devices, rng):
    """Widths below b* take the per-column path, so warming them must not
    compile GEMM buckets submit() would never dispatch."""
    mesh = make_mesh(8)
    a, X = make_operands(rng)
    engine = MatvecEngine(a, mesh, strategy="rowwise", promote=4, max_bucket=16)
    n = engine.warmup(widths=[1, 2, 3, 5])  # only 5 promotes (bucket 8)
    assert n == 2  # matvec + bucket-8 gemm; buckets 1/2 never compile
    baseline = engine.stats.compiles
    for w in (1, 2, 3, 5):
        engine.submit(X[:, :w]).result()
    assert engine.stats.compiles == baseline


def test_unsupported_combine_fails_at_construction(devices, rng):
    """A bad schedule name must fail when the engine is built, not
    requests deep at first-dispatch compile (and as a MatvecError, so the
    serve sweep's skip path catches it)."""
    mesh = make_mesh(8)
    a, _ = make_operands(rng)
    with pytest.raises(ConfigError, match="combine schedule"):
        MatvecEngine(a, mesh, strategy="rowwise", combine="psum_scatter")
    with pytest.raises(ConfigError, match="combine schedule"):
        MatvecEngine(a, mesh, strategy="blockwise", combine="nope")


def test_no_promotion_uses_single_executable(devices, rng):
    mesh = make_mesh(8)
    a, X = make_operands(rng)
    engine = MatvecEngine(a, mesh, strategy="rowwise", promote=None)
    Y = engine.submit(X[:, :6]).result()
    np.testing.assert_allclose(Y, a @ X[:, :6], rtol=1e-5)
    stats = engine.stats
    assert engine.n_executables == 1  # only the matvec program exists
    assert stats.dispatches == 6


def test_donation_flag_off_still_correct(devices, rng):
    mesh = make_mesh(8)
    a, X = make_operands(rng)
    engine = MatvecEngine(a, mesh, strategy="rowwise", donate=False, promote=2)
    np.testing.assert_allclose(
        engine.submit(X[:, :4]).result(), a @ X[:, :4], rtol=1e-5
    )


# -------------------------------------------------------- future semantics


def test_future_is_async_then_done(devices, rng):
    mesh = make_mesh(8)
    a, X = make_operands(rng)
    engine = MatvecEngine(a, mesh, strategy="rowwise", promote=2)
    fut = engine.submit(X[:, :4])
    vals = fut.device_values()
    assert vals and all(v.shape == (64, 4) for v in vals)  # padded view
    fut.result()
    assert fut.done()


def test_request_validation(devices, rng):
    mesh = make_mesh(8)
    a, _ = make_operands(rng)
    engine = MatvecEngine(a, mesh, strategy="rowwise")
    with pytest.raises(ConfigError):
        engine.submit(np.ones(32, np.float32))  # wrong k
    with pytest.raises(ConfigError):
        engine.submit(np.ones((32, 3), np.float32))
    with pytest.raises(ConfigError):
        engine.submit(np.ones((64, 0), np.float32))
    with pytest.raises(ConfigError):
        MatvecEngine(np.ones(8, np.float32), mesh)  # rank-1 A


# ------------------------------------------------ tuned-decision plumbing


def test_promote_auto_consults_tuning_cache(devices, rng, cache_path):
    mesh = make_mesh(8)
    a, X = make_operands(rng)
    cache = TuningCache.load(cache_path)
    cache.record(
        promote_key("rowwise", 64, 64, 8, "float32"),
        {"b_star": 3, "seq_time_s": 1e-5, "gemm_times": {"3": 1e-5}},
    )
    cache.save()
    reset_cache()
    engine = MatvecEngine(a, mesh, strategy="rowwise", promote="auto")
    assert engine.b_star == 3
    # b=3 now promotes: one bucket-4 GEMM dispatch, not 3 matvecs.
    engine.submit(X[:, :3]).result()
    assert engine.stats.dispatches == 1


def test_promote_auto_miss_uses_static_default(devices, rng, cache_path):
    mesh = make_mesh(8)
    a, _ = make_operands(rng)
    engine = MatvecEngine(a, mesh, strategy="rowwise", promote="auto")
    assert engine.b_star == DEFAULT_PROMOTE_B


def test_promote_measured_never_is_honored(devices, rng, cache_path):
    """b_star=null in the cache means promotion measurably never won —
    distinct from a miss: the engine must keep the per-column path."""
    mesh = make_mesh(8)
    a, X = make_operands(rng)
    cache = TuningCache.load(cache_path)
    cache.record(
        promote_key("rowwise", 64, 64, 8, "float32"),
        {"b_star": None, "seq_time_s": 1e-5, "gemm_times": {"4": 9.0}},
    )
    cache.save()
    reset_cache()
    engine = MatvecEngine(a, mesh, strategy="rowwise", promote="auto")
    assert engine.b_star is None
    engine.submit(X[:, :6]).result()
    assert engine.stats.dispatches == 6


def test_engine_combine_auto_resolves_both_paths(
    devices, rng, cache_path, monkeypatch
):
    """combine='auto' pins the matvec winner AND the gemm winner at
    construction; the promoted path must actually run the gemm one."""
    import matvec_mpi_multiplier_tpu.parallel.ring as ring

    mesh = make_mesh(8)
    a, X = make_operands(rng)
    cache = TuningCache.load(cache_path)
    cache.record(
        combine_key("matvec", "colwise", 64, 64, 8, "float32"),
        {"combine": "psum"},
    )
    cache.record(
        combine_key("gemm", "colwise", 64, 64, 8, "float32"),
        {"combine": "ring"},
    )
    cache.save()
    reset_cache()

    calls = []
    real = ring.ring_psum_scatter

    def spy(v, axes):
        calls.append(getattr(v, "ndim", None))
        return real(v, axes)

    monkeypatch.setattr(ring, "ring_psum_scatter", spy)
    engine = MatvecEngine(
        a, mesh, strategy="colwise", combine="auto", promote=4
    )
    assert engine._matvec_combine == "psum"
    assert engine._gemm_combine == "ring"
    Y = engine.submit(X[:, :8]).result()
    np.testing.assert_allclose(Y, a @ X[:, :8], rtol=1e-4)
    assert 2 in calls, "gemm dispatch did not route through the ring"
    calls.clear()
    y = engine.submit(X[:, 0]).result()
    np.testing.assert_allclose(y, a @ X[:, 0], rtol=1e-4)
    assert not calls, "matvec path must use its own (psum) winner"


def test_matvec_only_combine_falls_back_on_batched_path(devices, rng):
    """combine='ring' on rowwise is the matvec output gather; the batched
    path has no such schedule and must fall back to its default rather
    than refuse to build."""
    mesh = make_mesh(8)
    a, X = make_operands(rng)
    engine = MatvecEngine(a, mesh, strategy="rowwise", combine="ring", promote=2)
    assert engine._matvec_combine == "ring"
    assert engine._gemm_combine is None
    np.testing.assert_allclose(
        engine.submit(X[:, :4]).result(), a @ X[:, :4], rtol=1e-5
    )

"""staticcheck behavior tests: per-rule fixtures, engine mechanics, and
the lowered-HLO collective-schedule audit.

Layer 1 coverage contract (one table, every rule): each registered AST
rule must flag its known-bad fixture snippet AND stay quiet on the marked
(or structurally clean) twin — so the fixture table going stale relative
to the registry is itself a test failure. The seeded-violation corpus is
also run through the CLI (`python -m ... --rules --root ... --json`) and
compared finding-for-finding with the API — the two entry points
(scripts/tier1.sh fail-fast and this suite) must agree.

Layer 2: the audit must pass on the untouched tree against the committed
golden table, and a mutation that swaps a staged collective in
parallel/ring.py for one full-width ``jax.lax.all_gather`` must fail it —
the acceptance criterion that turns "overlap measures like the un-staged
baseline while claiming to overlap" into a red CI run.
"""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from matvec_mpi_multiplier_tpu.staticcheck import RULES, run_rules
from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
    AUDIT_CONFIGS,
    AUDIT_DEVICES,
    AuditConfig,
    lower_config,
    lowering_fingerprint,
    run_hlo_audit,
    write_golden,
)

REPO = Path(__file__).resolve().parent.parent

PKG = "matvec_mpi_multiplier_tpu"

# rule -> (repo-relative path in the rule's scope, bad snippet, clean twin).
# The clean twin differs only by the exemption marker (or the structurally
# clean form) — proving the marker contract, not just the detector.
RULE_FIXTURES = {
    "shard-map-direct": (
        f"{PKG}/models/seeded.py",
        "from jax.experimental import shard_map\n",
        "from matvec_mpi_multiplier_tpu.utils.compat import shard_map\n",
    ),
    "engine-host-sync": (
        f"{PKG}/engine/seeded.py",
        "import numpy as np\n"
        "def dispatch(y):\n"
        "    return np.asarray(y)\n",
        "import numpy as np\n"
        "def dispatch(y):\n"
        "    return np.asarray(y)  # sync-ok: seeded deliberate sync\n",
    ),
    "overlap-unchunked-collective": (
        f"{PKG}/parallel/ring.py",
        # the alias evasion the greps could not see through
        "from jax import lax as L\n"
        "def gather(x, ax):\n"
        "    return L.all_gather(x, ax, tiled=True)\n",
        "from jax import lax as L\n"
        "def gather(x, ax):\n"
        "    return L.all_gather(x, ax, tiled=True)  # overlap-ok: seeded\n",
    ),
    "hot-path-blocking-io": (
        f"{PKG}/obs/tracing.py",
        "import json\n"
        "def flush(path, payload):\n"
        "    json.dump(payload, open(path, 'w'))\n"
        "def flush_via_path(path, text):\n"
        "    with path.open('w') as fh:\n"     # the Path.open() spelling
        "        fh.write(text)\n",
        "import json\n"
        "def describe():\n"
        "    return 'the sink thread owns json.dump(payload, open(...))'\n",
    ),
    "fp64-implicit-promotion": (
        f"{PKG}/ops/seeded.py",
        "import jax.numpy as jnp\n"
        "def padding(n):\n"
        "    return jnp.zeros(n)\n",
        "import jax.numpy as jnp\n"
        "def padding(n, dtype):\n"
        "    return jnp.zeros(n, dtype)\n",
    ),
    "import-time-jnp": (
        f"{PKG}/ops/seeded.py",
        "import jax.numpy as jnp\n"
        "TABLE = jnp.arange(0, 8, 1, jnp.int32)\n",
        "import numpy as np\n"
        "TABLE = np.arange(0, 8, 1, np.int32)\n",
    ),
    "mutable-default-arg": (
        f"{PKG}/ops/seeded.py",
        "def accumulate(x, acc=[]):\n"
        "    acc.append(x)\n"
        "    return acc\n",
        "def accumulate(x, acc=None):\n"
        "    acc = [] if acc is None else acc\n"
        "    acc.append(x)\n"
        "    return acc\n",
    ),
    "silent-except": (
        f"{PKG}/tuning/seeded.py",
        # swallowed wholesale: no re-raise, no recording, no marker
        "def load(path):\n"
        "    try:\n"
        "        return int(path)\n"
        "    except Exception:\n"
        "        return None\n",
        "def load(path):\n"
        "    try:\n"
        "        return int(path)\n"
        "    except Exception:  # swallow-ok: seeded deliberate fallback\n"
        "        return None\n",
    ),
    "quant-fp64-scale": (
        f"{PKG}/ops/quantize.py",
        # host numpy's default float IS float64: a dtype-less asarray in
        # the quant scope silently doubles the scale plane and lies about
        # the error budget
        "import numpy as np\n"
        "def scales_for(amax):\n"
        "    return np.asarray(amax / 127.0)\n"
        "def widen(scales):\n"
        "    return scales.astype(np.float64)\n",
        "import numpy as np\n"
        "def scales_for(amax):\n"
        "    return np.asarray(amax / 127.0, dtype=np.float32)\n"
        "def widen(scales):\n"
        "    return scales.astype(np.float64)  # quant-ok: seeded deliberate f64 staging\n",
    ),
    "device-transfer-under-registry-lock": (
        f"{PKG}/engine/registry.py",
        # the swap-in under the held registry mutex: one tenant's
        # device_put freezes every other tenant's admission
        "import jax\n"
        "class Registry:\n"
        "    def admit(self, entry, payload, sharding):\n"
        "        with self._lock:\n"
        "            self._plan(entry)\n"
        "            entry.a = jax.device_put(payload, sharding)\n",
        # the discipline: plan victims under the lock, place after release
        "import jax\n"
        "class Registry:\n"
        "    def admit(self, entry, payload, sharding):\n"
        "        with self._lock:\n"
        "            self._plan(entry)\n"
        "        entry.a = jax.device_put(payload, sharding)\n",
    ),
    "measurement-in-admission-path": (
        f"{PKG}/engine/global_scheduler.py",
        # timing a dispatch inside admission: a perf_counter pair around
        # submit + the sync it needs puts a benchmark in front of every
        # request (admission consults predictions; the tuner measures)
        "import time\n"
        "def admit(self, engine, x):\n"
        "    t0 = time.perf_counter()\n"
        "    fut = engine.submit(x)\n"
        "    fut.block_until_ready()\n"
        "    self._observed = time.perf_counter() - t0\n"
        "    return fut\n",
        "import time\n"
        "def admit(self, engine, x):\n"
        "    t0 = time.perf_counter()  # admit-ok: seeded deliberate measurement\n"
        "    fut = engine.submit(x)\n"
        "    fut.block_until_ready()  # admit-ok: seeded deliberate sync\n"
        "    self._observed = time.perf_counter() - t0  # admit-ok: seeded deliberate measurement\n"
        "    return fut\n",
    ),
    "scheduler-lock-across-dispatch": (
        f"{PKG}/engine/scheduler.py",
        # dispatch under the held admission lock: a backpressure stall
        # would freeze every submitter
        "class Sched:\n"
        "    def flush(self):\n"
        "        with self._cond:\n"
        "            batch = list(self._pending)\n"
        "            return self.engine.submit(batch)\n",
        # the discipline: swap out under the lock, dispatch after release
        "class Sched:\n"
        "    def flush(self):\n"
        "        with self._cond:\n"
        "            batch = list(self._pending)\n"
        "        return self.engine.submit(batch)\n",
    ),
}

# The PR-6 scope-extension pins: the engine host-sync and hot-path I/O
# rules cover engine/scheduler.py by construction (engine/ prefix scope) —
# each gets its own known-bad fixture AT that path so a future scope
# narrowing cannot silently uncover the flush loop.
SCHEDULER_SCOPE_FIXTURES = {
    "engine-host-sync": (
        f"{PKG}/engine/scheduler.py",
        "import numpy as np\n"
        "def flush(self, batch):\n"
        "    return [np.asarray(p.block) for p in batch]\n",
        "import numpy as np\n"
        "def flush(self, batch):\n"
        "    return [np.asarray(p.block) for p in batch]  # sync-ok: seeded host staging\n",
    ),
    "hot-path-blocking-io": (
        f"{PKG}/engine/scheduler.py",
        "import json\n"
        "def flush(self, batch, path):\n"
        "    json.dump([p.width for p in batch], open(path, 'w'))\n",
        "import json\n"
        "def describe():\n"
        "    return 'batch logs go through obs/sink.py, never json.dump'\n",
    ),
}


def _seed(root: Path, rel: str, source: str) -> None:
    path = root / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(source)


def test_fixture_table_covers_every_rule():
    """Adding a rule without a known-bad fixture is itself a failure."""
    assert set(RULE_FIXTURES) == set(RULES), (
        "RULE_FIXTURES out of sync with the staticcheck rule registry"
    )


@pytest.mark.parametrize("rule", sorted(RULE_FIXTURES))
def test_rule_flags_bad_and_passes_clean(rule, tmp_path):
    rel, bad, clean = RULE_FIXTURES[rule]
    _seed(tmp_path, rel, bad)
    found = run_rules(root=tmp_path, rules=[rule])
    assert any(f.rule == rule and f.path == rel for f in found), (
        f"{rule} missed its known-bad fixture: {found}"
    )
    _seed(tmp_path, rel, clean)
    found = run_rules(root=tmp_path, rules=[rule])
    assert not [f for f in found if f.rule == rule], (
        f"{rule} flagged its clean/marked twin: {found}"
    )


@pytest.mark.parametrize("rule", sorted(SCHEDULER_SCOPE_FIXTURES))
def test_rule_covers_scheduler_module(rule, tmp_path):
    """The flush loop's home (engine/scheduler.py) is inside the engine
    rules' scope: a seeded violation there must be flagged, and its
    marked/clean twin must pass."""
    rel, bad, clean = SCHEDULER_SCOPE_FIXTURES[rule]
    _seed(tmp_path, rel, bad)
    found = run_rules(root=tmp_path, rules=[rule])
    assert any(f.rule == rule and f.path == rel for f in found), (
        f"{rule} does not cover {rel}: {found}"
    )
    _seed(tmp_path, rel, clean)
    found = run_rules(root=tmp_path, rules=[rule])
    assert not [f for f in found if f.rule == rule], found


def test_lock_rule_ignores_deferred_bodies_and_nonlock_contexts(tmp_path):
    """A function defined (not called) under the lock runs later — not a
    finding; a non-lock context manager (e.g. a span) is not a lock."""
    _seed(
        tmp_path, f"{PKG}/engine/scheduler.py",
        "class Sched:\n"
        "    def flush(self):\n"
        "        with self._cond:\n"
        "            def later():\n"
        "                return self.engine.submit(None)\n"
        "            self._callback = later\n"
        "        with self.trace.span('dispatch'):\n"
        "            return self.engine.submit(None)\n",
    )
    assert run_rules(
        root=tmp_path, rules=["scheduler-lock-across-dispatch"]
    ) == []


def test_shard_map_rule_catches_top_level_and_bare_alias(tmp_path):
    """The evasion spellings: the modern top-level `from jax import
    shard_map` (aliased, called by bare name) must be caught, while the
    compat-shim import resolves clean."""
    _seed(
        tmp_path, f"{PKG}/models/seeded.py",
        "from jax import shard_map as sm\n"
        "def build(fn, mesh):\n"
        "    return sm(fn, mesh=mesh)\n",
    )
    found = run_rules(root=tmp_path, rules=["shard-map-direct"])
    assert {f.line for f in found} == {1, 3}, found
    _seed(
        tmp_path, f"{PKG}/models/seeded.py",
        "from matvec_mpi_multiplier_tpu.utils.compat import shard_map\n"
        "def build(fn, mesh):\n"
        "    return shard_map(fn, mesh=mesh)\n",
    )
    assert run_rules(root=tmp_path, rules=["shard-map-direct"]) == []


def test_strings_and_docstrings_do_not_trip_rules(tmp_path):
    """The regex rules' false-positive class, now structurally impossible:
    forbidden patterns inside strings and docstrings are not code."""
    _seed(
        tmp_path, f"{PKG}/parallel/ring.py",
        '"""Never call jax.lax.all_gather( or jax.lax.psum( here."""\n'
        "PATTERN = 'jax.lax.all_gather(x)'\n",
    )
    _seed(
        tmp_path, f"{PKG}/engine/doc.py",
        '"""np.asarray(y) and y.block_until_ready() are forbidden."""\n'
        "RULE = 'jax.experimental.shard_map'\n",
    )
    assert run_rules(root=tmp_path) == []


def test_marker_without_reason_is_a_finding(tmp_path):
    _seed(
        tmp_path, f"{PKG}/engine/seeded.py",
        "import numpy as np\n"
        "def dispatch(y):\n"
        "    return np.asarray(y)  # sync-ok:\n",
    )
    found = run_rules(root=tmp_path)
    rules = {f.rule for f in found}
    # The empty marker still suppresses the sync finding but is itself
    # flagged — an escape hatch cannot be silent.
    assert rules == {"marker-missing-reason"}, found


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    _seed(tmp_path, f"{PKG}/ops/seeded.py", "def broken(:\n")
    found = run_rules(root=tmp_path)
    assert [f.rule for f in found] == ["parse-error"]


def test_cli_and_api_agree_on_seeded_corpus(tmp_path):
    """The two lint entry points (tier1.sh fail-fast → CLI; the suite →
    API) must return the same verdict on the same tree."""
    for rule, (rel, bad, _clean) in sorted(RULE_FIXTURES.items()):
        # One tree with every seeded violation; later seeds of the same
        # path overwrite — keep the union deterministic by suffixing.
        _seed(tmp_path, rel.replace("seeded", f"seeded_{rule[:8]}"), bad)
    api = run_rules(root=tmp_path)
    assert api, "seeded corpus produced no findings"
    proc = subprocess.run(
        [sys.executable, "-m", "matvec_mpi_multiplier_tpu.staticcheck",
         "--rules", "--root", str(tmp_path), "--json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stderr
    cli = json.loads(proc.stdout)["findings"]
    assert [(f["path"], f["line"], f["rule"]) for f in cli] == [
        (f.path, f.line, f.rule) for f in api
    ]


# ---------------------------------------------------------------- layer 2


def test_audit_table_covers_acceptance_family():
    """All three strategies, across the combine family the paper's
    schedule story names, at two staged depths."""
    strategies = {c.strategy for c in AUDIT_CONFIGS}
    assert strategies == {"rowwise", "colwise", "blockwise"}
    colwise = {
        c.combine + (f"@{c.stages}" if c.stages else "")
        for c in AUDIT_CONFIGS if c.strategy == "colwise"
    }
    assert {
        "psum_scatter", "ring", "a2a", "overlap@2", "overlap@4",
        "overlap_ring@2", "overlap_ring@4",
    } <= colwise
    for strategy in ("rowwise", "blockwise"):
        assert any(
            c.strategy == strategy and c.combine == "overlap"
            for c in AUDIT_CONFIGS
        )


def test_hlo_audit_clean_on_untouched_tree(devices):
    findings = run_hlo_audit()
    assert findings == [], "\n".join(
        f"{f.location}: [{f.rule}] {f.message}" for f in findings
    )


def test_mutation_full_width_gather_fails_audit(devices, monkeypatch):
    """Swap the staged gather in parallel/ring.py for ONE full-width
    jax.lax.all_gather: the audit must go red (S chunked collectives
    became a single full-width one) while the untouched tree passes."""
    import jax

    from matvec_mpi_multiplier_tpu.parallel import ring

    def full_width(a_blk, x_loc, gather_axes, kernel, stages,
                   reduce_axes=None):
        part = kernel(a_blk, x_loc)
        if reduce_axes is not None:
            part = jax.lax.psum(part, reduce_axes)
        return jax.lax.all_gather(part, gather_axes, tiled=True)

    monkeypatch.setattr(ring, "staged_overlap_gather", full_width)
    cfg = AuditConfig("rowwise", "overlap", 2)
    findings = run_hlo_audit(configs=[cfg], check_fingerprints=False)
    assert any(f.rule == "hlo-schedule" for f in findings), findings
    assert any(f.rule == "hlo-census" for f in findings), findings
    # And the same config passes un-mutated.
    monkeypatch.undo()
    assert run_hlo_audit(configs=[cfg], check_fingerprints=False) == []


def test_mutation_unchunked_scatter_fails_audit(devices, monkeypatch):
    """The colwise face: collapsing the S-stage scatter pipeline into one
    full-width psum_scatter breaks the overlap census pin."""
    import jax

    from matvec_mpi_multiplier_tpu.parallel import ring

    def full_width(a_panel, x_seg, axis_name, kernel, stages,
                   step="psum_scatter"):
        return jax.lax.psum_scatter(
            kernel(a_panel, x_seg), axis_name, tiled=True
        )

    monkeypatch.setattr(ring, "staged_overlap_scatter", full_width)
    cfg = AuditConfig("colwise", "overlap", 4)
    findings = run_hlo_audit(configs=[cfg], check_fingerprints=False)
    assert any(
        f.rule == "hlo-schedule" and "S=4" in f.message for f in findings
    ), findings


def test_audit_table_covers_storage_formats():
    """The quantized-storage cells (ISSUE 8): the rowwise format ladder
    plus the compensated pair on colwise and an int8 blockwise cell —
    and every native key keeps its historical no-suffix spelling, so the
    pre-quantization golden entries survive the schema bump."""
    storage_keys = {c.key for c in AUDIT_CONFIGS if c.storage != "native"}
    assert {
        "rowwise|gather|xla|int8", "rowwise|gather|xla|int8c",
        "rowwise|gather|xla|fp8", "colwise|psum_scatter|xla|int8",
        "colwise|psum_scatter|xla|int8c", "blockwise|gather|xla|int8",
    } == storage_keys
    for cfg in AUDIT_CONFIGS:
        if cfg.storage == "native":
            assert "|int8" not in cfg.key and "|fp8" not in cfg.key


def test_mutation_dequant_first_fails_census_gate(devices):
    """The 'silent early-dequant' failure mode: a quantized config whose
    kernel materializes the full dequantized A before the contraction
    stores ¼ the bytes but MOVES all of them. The census gate must flag
    its lowering, and pass the sanctioned tile-wise kernel."""
    from matvec_mpi_multiplier_tpu.ops.quantize import (
        matvec_quantized_dequant_first,
    )
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        early_dequant_findings,
    )

    mesh = make_mesh(AUDIT_DEVICES)
    for cfg in (
        AuditConfig("rowwise", "gather", storage="int8"),
        AuditConfig("colwise", "psum_scatter", storage="int8c"),
    ):
        bad = lower_config(
            cfg, mesh, kernel=matvec_quantized_dequant_first
        )
        findings = early_dequant_findings(cfg, bad, mesh)
        assert any(f.rule == "hlo-early-dequant" for f in findings), (
            f"{cfg.key}: dequant-first lowering not flagged"
        )
        clean = lower_config(cfg, mesh)
        assert early_dequant_findings(cfg, clean, mesh) == []


def test_storage_byte_ceiling_gate_wiring(devices, monkeypatch):
    """An absurdly tight ceiling must surface as hlo-storage-bytes — the
    gate reads the lowered module's parameter bytes, not the builder's
    intent."""
    from matvec_mpi_multiplier_tpu.staticcheck import hlo

    monkeypatch.setitem(hlo.STORAGE_BYTE_CEILING, "int8", 0.01)
    findings = run_hlo_audit(
        configs=[AuditConfig("rowwise", "gather", storage="int8")],
        check_fingerprints=False,
    )
    assert any(f.rule == "hlo-storage-bytes" for f in findings), findings


def test_fingerprint_stability_gate(devices):
    """Same config, two fresh builds → byte-identical lowering hashes (the
    engine-cache silent-recompile guard), and the audit's gate agrees."""
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(AUDIT_DEVICES)
    cfg = AuditConfig("colwise", "overlap", 2)
    assert lowering_fingerprint(lower_config(cfg, mesh)) == \
        lowering_fingerprint(lower_config(cfg, mesh))


def test_engine_cache_records_matching_fingerprints(devices):
    """Two independent engines compiling the same ExecKey must record the
    same lowering fingerprint — the cross-restart identity the AOT cache
    claims (engine/executables.py)."""
    from matvec_mpi_multiplier_tpu import MatvecEngine, make_mesh

    mesh = make_mesh(8)
    a = np.arange(64 * 64, dtype=np.float32).reshape(64, 64) / 64.0

    def fingerprints():
        engine = MatvecEngine(
            a, mesh, strategy="colwise", combine="psum_scatter",
            promote=None,
        )
        engine.warmup(widths=(1,))
        cache = engine._cache
        fps = {key: cache.fingerprint(key) for key in cache._executables}
        engine.close()
        return fps

    first, second = fingerprints(), fingerprints()
    assert first and first == second


def test_golden_roundtrip_and_drift_detection(devices, tmp_path):
    golden = tmp_path / "golden_schedule.json"
    cfg = AuditConfig("colwise", "psum_scatter")
    write_golden(path=golden)
    assert run_hlo_audit(
        golden_path=golden, configs=[cfg], check_fingerprints=False
    ) == []

    # Golden drift: a tampered census pin must surface as hlo-census.
    payload = json.loads(golden.read_text())
    payload["configs"][cfg.key]["census"] = {"all-gather": 3}
    golden.write_text(json.dumps(payload))
    findings = run_hlo_audit(
        golden_path=golden, configs=[cfg], check_fingerprints=False
    )
    assert any(f.rule == "hlo-census" for f in findings), findings

    # A stale pinned config (not in the audit table) is also drift.
    payload["configs"][cfg.key]["census"] = {"reduce-scatter": 1}
    payload["configs"]["colwise|retired_combine|xla"] = {"census": {}}
    golden.write_text(json.dumps(payload))
    findings = run_hlo_audit(
        golden_path=golden, configs=[cfg], check_fingerprints=False
    )
    assert any(
        f.rule == "hlo-golden" and "retired_combine" in f.message
        for f in findings
    ), findings


def test_empty_golden_configs_is_not_a_clean_audit(devices, tmp_path):
    """A golden file whose 'configs' object is empty (bad merge, hand
    edit) must read as every pin missing — never as a silently disabled
    pin layer."""
    golden = tmp_path / "golden_schedule.json"
    golden.write_text(json.dumps({"schema": 1, "configs": {}}))
    findings = run_hlo_audit(
        golden_path=golden,
        configs=[AuditConfig("colwise", "psum")],
        check_fingerprints=False,
    )
    assert any(
        f.rule == "hlo-golden" and "missing from the golden table"
        in f.message
        for f in findings
    ), findings


def test_missing_golden_is_a_finding(devices, tmp_path):
    findings = run_hlo_audit(
        golden_path=tmp_path / "nope.json",
        configs=[AuditConfig("colwise", "psum")],
        check_fingerprints=False,
    )
    assert any(
        f.rule == "hlo-golden" and "--write-golden" in f.message
        for f in findings
    ), findings

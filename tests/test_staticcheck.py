"""staticcheck behavior tests: per-rule fixtures, engine mechanics, and
the lowered-HLO collective-schedule audit.

Layer 1 coverage contract (one table, every rule): each registered AST
rule must flag its known-bad fixture snippet AND stay quiet on the marked
(or structurally clean) twin — so the fixture table going stale relative
to the registry is itself a test failure. The seeded-violation corpus is
also run through the CLI (`python -m ... --rules --root ... --json`) and
compared finding-for-finding with the API — the two entry points
(scripts/tier1.sh fail-fast and this suite) must agree.

Layer 2: the audit must pass on the untouched tree against the committed
golden table, and a mutation that swaps a staged collective in
parallel/ring.py for one full-width ``jax.lax.all_gather`` must fail it —
the acceptance criterion that turns "overlap measures like the un-staged
baseline while claiming to overlap" into a red CI run.
"""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from matvec_mpi_multiplier_tpu.staticcheck import RULES, run_rules
from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
    AUDIT_CONFIGS,
    AUDIT_DEVICES,
    AuditConfig,
    lower_config,
    lowering_fingerprint,
    run_hlo_audit,
    write_golden,
)

REPO = Path(__file__).resolve().parent.parent

PKG = "matvec_mpi_multiplier_tpu"

# rule -> (repo-relative path in the rule's scope, bad snippet, clean twin).
# The clean twin differs only by the exemption marker (or the structurally
# clean form) — proving the marker contract, not just the detector.
RULE_FIXTURES = {
    "shard-map-direct": (
        f"{PKG}/models/seeded.py",
        "from jax.experimental import shard_map\n",
        "from matvec_mpi_multiplier_tpu.utils.compat import shard_map\n",
    ),
    "engine-host-sync": (
        f"{PKG}/engine/seeded.py",
        "import numpy as np\n"
        "def dispatch(y):\n"
        "    return np.asarray(y)\n",
        "import numpy as np\n"
        "def dispatch(y):\n"
        "    return np.asarray(y)  # sync-ok: seeded deliberate sync\n",
    ),
    "overlap-unchunked-collective": (
        f"{PKG}/parallel/ring.py",
        # the alias evasion the greps could not see through
        "from jax import lax as L\n"
        "def gather(x, ax):\n"
        "    return L.all_gather(x, ax, tiled=True)\n",
        "from jax import lax as L\n"
        "def gather(x, ax):\n"
        "    return L.all_gather(x, ax, tiled=True)  # overlap-ok: seeded\n",
    ),
    "hot-path-blocking-io": (
        f"{PKG}/obs/tracing.py",
        "import json\n"
        "def flush(path, payload):\n"
        "    json.dump(payload, open(path, 'w'))\n"
        "def flush_via_path(path, text):\n"
        "    with path.open('w') as fh:\n"     # the Path.open() spelling
        "        fh.write(text)\n",
        "import json\n"
        "def describe():\n"
        "    return 'the sink thread owns json.dump(payload, open(...))'\n",
    ),
    "fp64-implicit-promotion": (
        f"{PKG}/ops/seeded.py",
        "import jax.numpy as jnp\n"
        "def padding(n):\n"
        "    return jnp.zeros(n)\n",
        "import jax.numpy as jnp\n"
        "def padding(n, dtype):\n"
        "    return jnp.zeros(n, dtype)\n",
    ),
    "import-time-jnp": (
        f"{PKG}/ops/seeded.py",
        "import jax.numpy as jnp\n"
        "TABLE = jnp.arange(0, 8, 1, jnp.int32)\n",
        "import numpy as np\n"
        "TABLE = np.arange(0, 8, 1, np.int32)\n",
    ),
    "mutable-default-arg": (
        f"{PKG}/ops/seeded.py",
        "def accumulate(x, acc=[]):\n"
        "    acc.append(x)\n"
        "    return acc\n",
        "def accumulate(x, acc=None):\n"
        "    acc = [] if acc is None else acc\n"
        "    acc.append(x)\n"
        "    return acc\n",
    ),
    "silent-except": (
        f"{PKG}/tuning/seeded.py",
        # swallowed wholesale: no re-raise, no recording, no marker
        "def load(path):\n"
        "    try:\n"
        "        return int(path)\n"
        "    except Exception:\n"
        "        return None\n",
        "def load(path):\n"
        "    try:\n"
        "        return int(path)\n"
        "    except Exception:  # swallow-ok: seeded deliberate fallback\n"
        "        return None\n",
    ),
    "quant-fp64-scale": (
        f"{PKG}/ops/quantize.py",
        # host numpy's default float IS float64: a dtype-less asarray in
        # the quant scope silently doubles the scale plane and lies about
        # the error budget
        "import numpy as np\n"
        "def scales_for(amax):\n"
        "    return np.asarray(amax / 127.0)\n"
        "def widen(scales):\n"
        "    return scales.astype(np.float64)\n",
        "import numpy as np\n"
        "def scales_for(amax):\n"
        "    return np.asarray(amax / 127.0, dtype=np.float32)\n"
        "def widen(scales):\n"
        "    return scales.astype(np.float64)  # quant-ok: seeded deliberate f64 staging\n",
    ),
    "device-transfer-under-registry-lock": (
        f"{PKG}/engine/registry.py",
        # the swap-in under the held registry mutex: one tenant's
        # device_put freezes every other tenant's admission
        "import jax\n"
        "class Registry:\n"
        "    def admit(self, entry, payload, sharding):\n"
        "        with self._lock:\n"
        "            self._plan(entry)\n"
        "            entry.a = jax.device_put(payload, sharding)\n",
        # the discipline: plan victims under the lock, place after release
        "import jax\n"
        "class Registry:\n"
        "    def admit(self, entry, payload, sharding):\n"
        "        with self._lock:\n"
        "            self._plan(entry)\n"
        "        entry.a = jax.device_put(payload, sharding)\n",
    ),
    "measurement-in-admission-path": (
        f"{PKG}/engine/global_scheduler.py",
        # timing a dispatch inside admission: a perf_counter pair around
        # submit + the sync it needs puts a benchmark in front of every
        # request (admission consults predictions; the tuner measures)
        "import time\n"
        "def admit(self, engine, x):\n"
        "    t0 = time.perf_counter()\n"
        "    fut = engine.submit(x)\n"
        "    fut.block_until_ready()\n"
        "    self._observed = time.perf_counter() - t0\n"
        "    return fut\n",
        "import time\n"
        "def admit(self, engine, x):\n"
        "    t0 = time.perf_counter()  # admit-ok: seeded deliberate measurement\n"
        "    fut = engine.submit(x)\n"
        "    fut.block_until_ready()  # admit-ok: seeded deliberate sync\n"
        "    self._observed = time.perf_counter() - t0  # admit-ok: seeded deliberate measurement\n"
        "    return fut\n",
    ),
    "lock-mixed-guard": (
        f"{PKG}/engine/seeded.py",
        # written under the lock in charge(), read bare in total() — the
        # torn/stale-state shape the lock-graph auditor infers per class
        "import threading\n"
        "class Ledger:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._charged = 0\n"
        "    def charge(self, n):\n"
        "        with self._lock:\n"
        "            self._charged += n\n"
        "    def total(self):\n"
        "        return self._charged\n",
        "import threading\n"
        "class Ledger:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._charged = 0\n"
        "    def charge(self, n):\n"
        "        with self._lock:\n"
        "            self._charged += n\n"
        "    def total(self):\n"
        "        return self._charged  # unguarded-ok: seeded monotone snapshot read\n",
    ),
    "lock-order-inversion": (
        f"{PKG}/engine/seeded.py",
        # registry-lock -> engine-lock via place(), engine-lock ->
        # registry-lock via charge(): a cycle two threads can deadlock on
        "import threading\n"
        "class SeededRegistry:\n"
        "    def __init__(self, engine):\n"
        "        self._registry_lock = threading.Lock()\n"
        "        self.engine = engine\n"
        "    def admit(self):\n"
        "        with self._registry_lock:\n"
        "            self.engine.seeded_place()\n"
        "    def seeded_charge(self):\n"
        "        with self._registry_lock:\n"
        "            pass\n"
        "class SeededEngine:\n"
        "    def __init__(self, registry):\n"
        "        self._residency_lock = threading.Lock()\n"
        "        self.registry = registry\n"
        "    def seeded_place(self):\n"
        "        with self._residency_lock:\n"
        "            pass\n"
        "    def release(self):\n"
        "        with self._residency_lock:\n"
        "            self.registry.seeded_charge()\n",
        # the discipline: the cross-lock call moves after release (the
        # charge no longer happens under the residency lock)
        "import threading\n"
        "class SeededRegistry:\n"
        "    def __init__(self, engine):\n"
        "        self._registry_lock = threading.Lock()\n"
        "        self.engine = engine\n"
        "    def admit(self):\n"
        "        with self._registry_lock:\n"
        "            self.engine.seeded_place()\n"
        "    def seeded_charge(self):\n"
        "        with self._registry_lock:\n"
        "            pass\n"
        "class SeededEngine:\n"
        "    def __init__(self, registry):\n"
        "        self._residency_lock = threading.Lock()\n"
        "        self.registry = registry\n"
        "    def seeded_place(self):\n"
        "        with self._residency_lock:\n"
        "            pass\n"
        "    def release(self):\n"
        "        with self._residency_lock:\n"
        "            pass\n"
        "        self.registry.seeded_charge()\n",
    ),
    "callback-under-lock": (
        f"{PKG}/engine/seeded.py",
        # the PR 9 ledger-bug shape: the residency listener fires (via a
        # helper) while the residency bookkeeping lock is held
        "import threading\n"
        "class SeededEngine:\n"
        "    def __init__(self, listener):\n"
        "        self._residency_lock = threading.Lock()\n"
        "        self._listener = listener\n"
        "        self._bytes = 0\n"
        "    def _notify(self, delta):\n"
        "        self._listener(delta, 'resident')\n"
        "    def ensure(self, delta):\n"
        "        with self._residency_lock:\n"
        "            self._bytes += delta\n"
        "            self._notify(delta)\n",
        # the discipline: bookkeeping under the lock, callback after
        "import threading\n"
        "class SeededEngine:\n"
        "    def __init__(self, listener):\n"
        "        self._residency_lock = threading.Lock()\n"
        "        self._listener = listener\n"
        "        self._bytes = 0\n"
        "    def _notify(self, delta):\n"
        "        self._listener(delta, 'resident')\n"
        "    def ensure(self, delta):\n"
        "        with self._residency_lock:\n"
        "            self._bytes += delta\n"
        "        self._notify(delta)\n",
    ),
    "scheduler-lock-across-dispatch": (
        f"{PKG}/engine/scheduler.py",
        # dispatch under the held admission lock: a backpressure stall
        # would freeze every submitter
        "class Sched:\n"
        "    def flush(self):\n"
        "        with self._cond:\n"
        "            batch = list(self._pending)\n"
        "            return self.engine.submit(batch)\n",
        # the discipline: swap out under the lock, dispatch after release
        "class Sched:\n"
        "    def flush(self):\n"
        "        with self._cond:\n"
        "            batch = list(self._pending)\n"
        "        return self.engine.submit(batch)\n",
    ),
    "metric-label-cardinality": (
        f"{PKG}/engine/seeded.py",
        # a per-request metric name: one live series per request id,
        # unbounded — the snapshot grows with traffic forever
        "class Serve:\n"
        "    def drain(self, batch):\n"
        "        for req in batch:\n"
        "            self.metrics.counter(\n"
        "                f'req_total{{id=\"{req.rid}\"}}', 'per-request'\n"
        "            ).inc()\n",
        # the known-clean shape: a bounded source, marked with the reason
        "class Serve:\n"
        "    def register_all(self, tenant_ids):\n"
        "        for tid in tenant_ids:\n"
        "            self.metrics.counter(  # cardinality-ok: seeded bounded tenant fleet\n"
        "                f'tenant_requests_total{{tenant=\"{tid}\"}}',\n"
        "                'per-tenant',\n"
        "            ).inc()\n",
    ),
    "traced-python-branch": (
        f"{PKG}/ops/seeded.py",
        # a Python `if` on a traced value: TracerBoolConversionError at
        # trace time, or a silently specialized branch if it concretizes
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    if x.sum() > 0:\n"
        "        return x\n"
        "    return -x\n",
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    if x.sum() > 0:  # traced-branch-ok: seeded sign dispatch\n"
        "        return x\n"
        "    return -x\n",
    ),
    "weak-type-cache-split": (
        f"{PKG}/ops/seeded.py",
        # a bare Python float reaching a jitted arg: weak-typed avals
        # split the compile cache against strongly-typed callers
        "import jax\n"
        "@jax.jit\n"
        "def g(x, scale):\n"
        "    return x * scale\n"
        "def serve(x):\n"
        "    s = 0.5\n"
        "    return g(x, s)\n",
        # the discipline: pin the dtype before the call boundary
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def g(x, scale):\n"
        "    return x * scale\n"
        "def serve(x):\n"
        "    s = jnp.float32(0.5)\n"
        "    return g(x, s)\n",
    ),
    "unhashable-static-arg": (
        f"{PKG}/ops/seeded.py",
        # a list into a static_argnames position: TypeError (unhashable)
        # at dispatch — static args key the compile cache by hash
        "import jax\n"
        "def f(x, tiles):\n"
        "    return x\n"
        "g = jax.jit(f, static_argnames=('tiles',))\n"
        "def serve(x):\n"
        "    return g(x, tiles=[8, 16])\n",
        "import jax\n"
        "def f(x, tiles):\n"
        "    return x\n"
        "g = jax.jit(f, static_argnames=('tiles',))\n"
        "def serve(x):\n"
        "    return g(x, tiles=(8, 16))\n",
    ),
    "host-sync-on-tracer": (
        f"{PKG}/engine/seeded.py",
        # float() on a tracer inside a jitted body: a host materialization
        # the trace cannot express — ConcretizationTypeError at trace time
        "import jax\n"
        "@jax.jit\n"
        "def norm(x):\n"
        "    s = float(x[0])\n"
        "    return s\n",
        "import jax\n"
        "@jax.jit\n"
        "def norm(x):\n"
        "    s = float(x[0])  # tracer-sync-ok: seeded deliberate abstraction break\n"
        "    return s\n",
    ),
}

# The PR-6 scope-extension pins: the engine host-sync and hot-path I/O
# rules cover engine/scheduler.py by construction (engine/ prefix scope) —
# each gets its own known-bad fixture AT that path so a future scope
# narrowing cannot silently uncover the flush loop.
SCHEDULER_SCOPE_FIXTURES = {
    "engine-host-sync": (
        f"{PKG}/engine/scheduler.py",
        "import numpy as np\n"
        "def flush(self, batch):\n"
        "    return [np.asarray(p.block) for p in batch]\n",
        "import numpy as np\n"
        "def flush(self, batch):\n"
        "    return [np.asarray(p.block) for p in batch]  # sync-ok: seeded host staging\n",
    ),
    "hot-path-blocking-io": (
        f"{PKG}/engine/scheduler.py",
        "import json\n"
        "def flush(self, batch, path):\n"
        "    json.dump([p.width for p in batch], open(path, 'w'))\n",
        "import json\n"
        "def describe():\n"
        "    return 'batch logs go through obs/sink.py, never json.dump'\n",
    ),
}


def _seed(root: Path, rel: str, source: str) -> None:
    path = root / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(source)


def test_fixture_table_covers_every_rule():
    """Adding a rule without a known-bad fixture is itself a failure."""
    assert set(RULE_FIXTURES) == set(RULES), (
        "RULE_FIXTURES out of sync with the staticcheck rule registry"
    )


@pytest.mark.parametrize("rule", sorted(RULE_FIXTURES))
def test_rule_flags_bad_and_passes_clean(rule, tmp_path):
    rel, bad, clean = RULE_FIXTURES[rule]
    _seed(tmp_path, rel, bad)
    found = run_rules(root=tmp_path, rules=[rule])
    assert any(f.rule == rule and f.path == rel for f in found), (
        f"{rule} missed its known-bad fixture: {found}"
    )
    _seed(tmp_path, rel, clean)
    found = run_rules(root=tmp_path, rules=[rule])
    assert not [f for f in found if f.rule == rule], (
        f"{rule} flagged its clean/marked twin: {found}"
    )


@pytest.mark.parametrize("rule", sorted(SCHEDULER_SCOPE_FIXTURES))
def test_rule_covers_scheduler_module(rule, tmp_path):
    """The flush loop's home (engine/scheduler.py) is inside the engine
    rules' scope: a seeded violation there must be flagged, and its
    marked/clean twin must pass."""
    rel, bad, clean = SCHEDULER_SCOPE_FIXTURES[rule]
    _seed(tmp_path, rel, bad)
    found = run_rules(root=tmp_path, rules=[rule])
    assert any(f.rule == rule and f.path == rel for f in found), (
        f"{rule} does not cover {rel}: {found}"
    )
    _seed(tmp_path, rel, clean)
    found = run_rules(root=tmp_path, rules=[rule])
    assert not [f for f in found if f.rule == rule], found


def test_lock_rule_ignores_deferred_bodies_and_nonlock_contexts(tmp_path):
    """A function defined (not called) under the lock runs later — not a
    finding; a non-lock context manager (e.g. a span) is not a lock."""
    _seed(
        tmp_path, f"{PKG}/engine/scheduler.py",
        "class Sched:\n"
        "    def flush(self):\n"
        "        with self._cond:\n"
        "            def later():\n"
        "                return self.engine.submit(None)\n"
        "            self._callback = later\n"
        "        with self.trace.span('dispatch'):\n"
        "            return self.engine.submit(None)\n",
    )
    assert run_rules(
        root=tmp_path, rules=["scheduler-lock-across-dispatch"]
    ) == []


def test_shard_map_rule_catches_top_level_and_bare_alias(tmp_path):
    """The evasion spellings: the modern top-level `from jax import
    shard_map` (aliased, called by bare name) must be caught, while the
    compat-shim import resolves clean."""
    _seed(
        tmp_path, f"{PKG}/models/seeded.py",
        "from jax import shard_map as sm\n"
        "def build(fn, mesh):\n"
        "    return sm(fn, mesh=mesh)\n",
    )
    found = run_rules(root=tmp_path, rules=["shard-map-direct"])
    assert {f.line for f in found} == {1, 3}, found
    _seed(
        tmp_path, f"{PKG}/models/seeded.py",
        "from matvec_mpi_multiplier_tpu.utils.compat import shard_map\n"
        "def build(fn, mesh):\n"
        "    return shard_map(fn, mesh=mesh)\n",
    )
    assert run_rules(root=tmp_path, rules=["shard-map-direct"]) == []


def test_strings_and_docstrings_do_not_trip_rules(tmp_path):
    """The regex rules' false-positive class, now structurally impossible:
    forbidden patterns inside strings and docstrings are not code."""
    _seed(
        tmp_path, f"{PKG}/parallel/ring.py",
        '"""Never call jax.lax.all_gather( or jax.lax.psum( here."""\n'
        "PATTERN = 'jax.lax.all_gather(x)'\n",
    )
    _seed(
        tmp_path, f"{PKG}/engine/doc.py",
        '"""np.asarray(y) and y.block_until_ready() are forbidden."""\n'
        "RULE = 'jax.experimental.shard_map'\n",
    )
    assert run_rules(root=tmp_path) == []


def test_marker_without_reason_is_a_finding(tmp_path):
    _seed(
        tmp_path, f"{PKG}/engine/seeded.py",
        "import numpy as np\n"
        "def dispatch(y):\n"
        "    return np.asarray(y)  # sync-ok:\n",
    )
    found = run_rules(root=tmp_path)
    rules = {f.rule for f in found}
    # The empty marker still suppresses the sync finding but is itself
    # flagged — an escape hatch cannot be silent.
    assert rules == {"marker-missing-reason"}, found


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    _seed(tmp_path, f"{PKG}/ops/seeded.py", "def broken(:\n")
    found = run_rules(root=tmp_path)
    assert [f.rule for f in found] == ["parse-error"]


def test_cli_and_api_agree_on_seeded_corpus(tmp_path):
    """The two lint entry points (tier1.sh fail-fast → CLI; the suite →
    API) must return the same verdict on the same tree."""
    for rule, (rel, bad, _clean) in sorted(RULE_FIXTURES.items()):
        # One tree with every seeded violation; later seeds of the same
        # path overwrite — keep the union deterministic by suffixing.
        _seed(tmp_path, rel.replace("seeded", f"seeded_{rule[:8]}"), bad)
    api = run_rules(root=tmp_path)
    assert api, "seeded corpus produced no findings"
    proc = subprocess.run(
        [sys.executable, "-m", "matvec_mpi_multiplier_tpu.staticcheck",
         "--rules", "--root", str(tmp_path), "--json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stderr
    cli = json.loads(proc.stdout)["findings"]
    assert [(f["path"], f["line"], f["rule"]) for f in cli] == [
        (f.path, f.line, f.rule) for f in api
    ]


# ----------------------------------------------------- lock-graph auditor


def test_lockgraph_clean_on_tree():
    """The merge acceptance bar: zero bare lock-graph findings on the
    real tree — every deliberate exception carries a reasoned marker
    (AST-only; no backend init)."""
    from matvec_mpi_multiplier_tpu.staticcheck import LOCKGRAPH_RULES

    findings = run_rules(rules=list(LOCKGRAPH_RULES))
    assert findings == [], "\n".join(
        f"{f.location}: [{f.rule}] {f.message}" for f in findings
    )


def test_lockgraph_cross_file_inversion_corpus(tmp_path):
    """The order graph spans files: registry-lock -> engine-lock in one
    module, engine-lock -> registry-lock in another, and the cycle is
    reported in both."""
    _seed(
        tmp_path, f"{PKG}/engine/seeded_registry.py",
        "import threading\n"
        "class SeededRegistry:\n"
        "    def __init__(self, engine):\n"
        "        self._registry_lock = threading.Lock()\n"
        "        self.engine = engine\n"
        "    def admit(self):\n"
        "        with self._registry_lock:\n"
        "            self.engine.seeded_place()\n"
        "    def seeded_charge(self):\n"
        "        with self._registry_lock:\n"
        "            pass\n",
    )
    _seed(
        tmp_path, f"{PKG}/engine/seeded_engine.py",
        "import threading\n"
        "class SeededEngine:\n"
        "    def __init__(self):\n"
        "        self._residency_lock = threading.Lock()\n"
        "    def seeded_place(self):\n"
        "        with self._residency_lock:\n"
        "            pass\n"
        "    def release(self, registry):\n"
        "        with self._residency_lock:\n"
        "            registry.seeded_charge()\n",
    )
    found = run_rules(root=tmp_path, rules=["lock-order-inversion"])
    assert {f.path for f in found} == {
        f"{PKG}/engine/seeded_registry.py",
        f"{PKG}/engine/seeded_engine.py",
    }, found
    for f in found:
        assert "_registry_lock" in f.message
        assert "_residency_lock" in f.message


def test_lockgraph_unannotated_direct_acquisition_inversion(tmp_path):
    """AB/BA through DIRECT `with self.other._x_lock:` acquisitions on
    UNANNOTATED attributes (the repo's dominant constructor style): the
    placeholder owner must unify with the class owning that uniquely
    named lock, or the deadlock is invisible."""
    _seed(
        tmp_path, f"{PKG}/engine/seeded.py",
        "import threading\n"
        "class SeededRegistry:\n"
        "    def __init__(self, engine):\n"
        "        self._registry_lock = threading.Lock()\n"
        "        self.engine = engine\n"
        "    def admit(self):\n"
        "        with self._registry_lock:\n"
        "            with self.engine._residency_lock:\n"
        "                pass\n"
        "class SeededEngine:\n"
        "    def __init__(self, registry):\n"
        "        self._residency_lock = threading.Lock()\n"
        "        self.registry = registry\n"
        "    def release(self):\n"
        "        with self._residency_lock:\n"
        "            with self.registry._registry_lock:\n"
        "                pass\n",
    )
    found = run_rules(root=tmp_path, rules=["lock-order-inversion"])
    assert found, "unannotated direct AB/BA went undetected"
    assert all(
        "_registry_lock" in f.message and "_residency_lock" in f.message
        for f in found
    ), found


def test_lockgraph_local_rooted_acquisition_inversion(tmp_path):
    """A lock reached through a LOCAL/parameter (`with eng._b_lock:`)
    still enters the order graph via unique-name unification — AB/BA
    through locals is the commonest real spelling."""
    _seed(
        tmp_path, f"{PKG}/engine/seeded.py",
        "import threading\n"
        "class SeededA:\n"
        "    def __init__(self):\n"
        "        self._alpha_lock = threading.Lock()\n"
        "    def forward(self, peer):\n"
        "        with self._alpha_lock:\n"
        "            with peer._beta_lock:\n"
        "                pass\n"
        "class SeededB:\n"
        "    def __init__(self):\n"
        "        self._beta_lock = threading.Lock()\n"
        "    def backward(self, peer):\n"
        "        with self._beta_lock:\n"
        "            with peer._alpha_lock:\n"
        "                pass\n",
    )
    found = run_rules(root=tmp_path, rules=["lock-order-inversion"])
    assert found, "local-rooted AB/BA went undetected"


def test_lockgraph_no_phantom_edges_from_locked_helpers(tmp_path):
    """A `*_locked` helper on a TWO-lock class is guarded by what its
    callers actually hold — the analyzer must not assume both own locks
    and fabricate an impossible deadlock cycle."""
    _seed(
        tmp_path, f"{PKG}/engine/seeded.py",
        "import threading\n"
        "class SeededEng:\n"
        "    def __init__(self, other):\n"
        "        self._gamma_lock = threading.Lock()\n"
        "        self._delta_lock = threading.Lock()\n"
        "        self.other = other\n"
        "    def _bump_locked(self):\n"
        "        with self.other._epsilon_lock:\n"
        "            pass\n"
        "    def bump(self):\n"
        "        with self._gamma_lock:\n"
        "            self._bump_locked()\n"
        "class SeededOther:\n"
        "    def __init__(self, eng):\n"
        "        self._epsilon_lock = threading.Lock()\n"
        "        self.eng = eng\n"
        "    def touch(self):\n"
        "        with self._epsilon_lock:\n"
        "            with self.eng._delta_lock:\n"
        "                pass\n",
    )
    # The only real order is gamma -> epsilon and epsilon -> delta: no
    # execution path holds _delta_lock while acquiring _epsilon_lock, so
    # there is no cycle — a finding here is a phantom edge.
    found = run_rules(root=tmp_path, rules=["lock-order-inversion"])
    assert found == [], found


def test_lockgraph_marker_drops_an_edge(tmp_path):
    """A `# lock-order-ok: <reason>` on an edge's acquisition/call site
    removes that edge BEFORE cycle detection — the documented-safe
    ordering breaks the cycle for both files."""
    _, bad, _clean = RULE_FIXTURES["lock-order-inversion"]
    marked = bad.replace(
        "            self.registry.seeded_charge()\n",
        "            self.registry.seeded_charge()  # lock-order-ok: seeded proven-safe ordering\n",
    )
    assert marked != bad
    _seed(tmp_path, f"{PKG}/engine/seeded.py", marked)
    assert run_rules(root=tmp_path, rules=["lock-order-inversion"]) == []


def test_mutation_pr9_listener_under_lock_goes_red(tmp_path):
    """Re-introducing the PR 9 ledger-bug shape — the engine's
    residency listener fired (through the notify helper) while the
    residency bookkeeping lock is held — turns the auditor red; the
    shipped discipline (notify after release) stays green."""
    shape = (
        "import threading\n"
        "class Engine:\n"
        "    def __init__(self, residency_listener):\n"
        "        self._residency_lock = threading.Lock()\n"
        "        self._residency_listener = residency_listener\n"
        "        self._a = None\n"
        "    def _notify_residency(self, delta, reason):\n"
        "        if self._residency_listener is not None:\n"
        "            self._residency_listener(delta, reason)\n"
        "    def ensure_resident(self, placed, nbytes):\n"
        "        with self._residency_lock:\n"
        "            self._a = placed\n"
        "{indent}self._notify_residency(nbytes, 'resident')\n"
    )
    _seed(
        tmp_path, f"{PKG}/engine/seeded.py",
        shape.format(indent="            "),  # under the lock: the bug
    )
    found = run_rules(root=tmp_path, rules=["callback-under-lock"])
    assert any(
        f.rule == "callback-under-lock"
        and "_residency_listener" in f.message
        for f in found
    ), found
    _seed(
        tmp_path, f"{PKG}/engine/seeded.py",
        shape.format(indent="        "),      # after release: the fix
    )
    assert run_rules(root=tmp_path, rules=["callback-under-lock"]) == []


def test_lockgraph_locked_helper_convention(tmp_path):
    """`*_locked` helpers run with the caller's lock held: accesses in
    their bodies are guarded, and CALLING one bare is itself a
    finding."""
    _seed(
        tmp_path, f"{PKG}/engine/seeded.py",
        "import threading\n"
        "class Sched:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._pending = []\n"
        "    def _take_locked(self):\n"
        "        batch = self._pending\n"
        "        self._pending = []\n"
        "        return batch\n"
        "    def submit(self, item):\n"
        "        with self._lock:\n"
        "            self._pending.append(item)\n"
        "    def flush(self):\n"
        "        with self._lock:\n"
        "            batch = self._take_locked()\n"
        "        return batch\n",
    )
    assert run_rules(root=tmp_path, rules=["lock-mixed-guard"]) == []
    _seed(
        tmp_path, f"{PKG}/engine/seeded.py",
        "import threading\n"
        "class Sched:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._pending = []\n"
        "    def _take_locked(self):\n"
        "        batch = self._pending\n"
        "        self._pending = []\n"
        "        return batch\n"
        "    def submit(self, item):\n"
        "        with self._lock:\n"
        "            self._pending.append(item)\n"
        "    def flush(self):\n"
        "        return self._take_locked()\n",
    )
    found = run_rules(root=tmp_path, rules=["lock-mixed-guard"])
    assert any("*_locked helper" in f.message for f in found), found


def test_lockgraph_multi_item_with_is_an_ordered_acquisition(tmp_path):
    """`with self._a_lock, self._b_lock:` acquires left-to-right while
    holding the earlier items — paired with a `b then a` path elsewhere
    it is the textbook AB/BA inversion and must be found."""
    _seed(
        tmp_path, f"{PKG}/engine/seeded.py",
        "import threading\n"
        "class Pair:\n"
        "    def __init__(self):\n"
        "        self._a_lock = threading.Lock()\n"
        "        self._b_lock = threading.Lock()\n"
        "    def forward(self):\n"
        "        with self._a_lock, self._b_lock:\n"
        "            pass\n"
        "    def backward(self):\n"
        "        with self._b_lock:\n"
        "            with self._a_lock:\n"
        "                pass\n",
    )
    found = run_rules(root=tmp_path, rules=["lock-order-inversion"])
    assert found, "AB/BA via a multi-item with went undetected"
    assert all("_a_lock" in f.message and "_b_lock" in f.message
               for f in found), found


def test_lockgraph_wrong_lock_read_of_helper_written_attr(tmp_path):
    """An attribute written only inside a `*_locked` helper is guarded
    by the class's own locks — reading it under a DIFFERENT object's
    lock is still a bare access and must be flagged."""
    _seed(
        tmp_path, f"{PKG}/engine/seeded.py",
        "import threading\n"
        "class Counter:\n"
        "    def __init__(self, other):\n"
        "        self._state_lock = threading.Lock()\n"
        "        self.other = other\n"
        "        self._count = 0\n"
        "    def _bump_locked(self):\n"
        "        self._count += 1\n"
        "    def bump(self):\n"
        "        with self._state_lock:\n"
        "            self._bump_locked()\n"
        "    def peek(self):\n"
        "        with self.other._foreign_lock:\n"
        "            return self._count\n",
    )
    found = run_rules(root=tmp_path, rules=["lock-mixed-guard"])
    assert any(
        "_count" in f.message and f.line == 14 for f in found
    ), found


def test_lockgraph_bare_invocation_of_guarded_callable_is_a_read(tmp_path):
    """Calling `self._listener()` IS reading `_listener`: a callable
    attribute written under the lock but invoked bare must be flagged
    like any other mixed access."""
    _seed(
        tmp_path, f"{PKG}/engine/seeded.py",
        "import threading\n"
        "class Notifier:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._listener = None\n"
        "    def set_listener(self, fn):\n"
        "        with self._lock:\n"
        "            self._listener = fn\n"
        "    def fire(self):\n"
        "        self._listener()\n",
    )
    found = run_rules(root=tmp_path, rules=["lock-mixed-guard"])
    assert any(
        "_listener" in f.message and f.line == 10 for f in found
    ), found


def test_lockgraph_marker_inside_with_body_does_not_exempt_the_edge(
    tmp_path,
):
    """Edges anchor to the `with` head's context expression, so a
    marker on an unrelated line INSIDE the block cannot silently exempt
    the acquisition edge recorded at its head."""
    _, bad, _clean = RULE_FIXTURES["lock-order-inversion"]
    # The marker lands inside a with BODY (on the pass statement of
    # seeded_charge), not on any acquisition/call edge site — the cycle
    # must still be found.
    marked = bad.replace(
        "    def seeded_charge(self):\n"
        "        with self._registry_lock:\n"
        "            pass\n",
        "    def seeded_charge(self):\n"
        "        with self._registry_lock:\n"
        "            pass  # lock-order-ok: seeded comment on an unrelated body line\n",
    )
    assert marked != bad
    _seed(tmp_path, f"{PKG}/engine/seeded.py", marked)
    found = run_rules(root=tmp_path, rules=["lock-order-inversion"])
    assert found, "a body-line marker exempted the whole with's edges"


def test_lockgraph_wrong_lock_message_names_the_held_lock(tmp_path):
    _seed(
        tmp_path, f"{PKG}/engine/seeded.py",
        "import threading\n"
        "class Counter:\n"
        "    def __init__(self, other):\n"
        "        self._state_lock = threading.Lock()\n"
        "        self.other = other\n"
        "        self._count = 0\n"
        "    def bump(self):\n"
        "        with self._state_lock:\n"
        "            self._count += 1\n"
        "    def peek(self):\n"
        "        with self.other._foreign_lock:\n"
        "            return self._count\n",
    )
    found = run_rules(root=tmp_path, rules=["lock-mixed-guard"])
    assert any(
        "holding only" in f.message and "_foreign_lock" in f.message
        for f in found
    ), found


def test_lockgraph_findings_carry_marker_and_severity(tmp_path):
    rel, bad, _clean = RULE_FIXTURES["lock-mixed-guard"]
    _seed(tmp_path, rel, bad)
    found = run_rules(root=tmp_path, rules=["lock-mixed-guard"])
    assert found and all(
        f.severity == "error" and f.marker == "unguarded-ok" for f in found
    ), found


# ---------------------------------------------------------------- layer 2


def test_audit_table_covers_acceptance_family():
    """All three strategies, across the combine family the paper's
    schedule story names, at two staged depths."""
    strategies = {c.strategy for c in AUDIT_CONFIGS}
    assert strategies == {"rowwise", "colwise", "blockwise"}
    colwise = {
        c.combine + (f"@{c.stages}" if c.stages else "")
        for c in AUDIT_CONFIGS if c.strategy == "colwise"
    }
    assert {
        "psum_scatter", "ring", "a2a", "overlap@2", "overlap@4",
        "overlap_ring@2", "overlap_ring@4",
    } <= colwise
    for strategy in ("rowwise", "blockwise"):
        assert any(
            c.strategy == strategy and c.combine == "overlap"
            for c in AUDIT_CONFIGS
        )


def test_hlo_audit_clean_on_untouched_tree(devices):
    findings = run_hlo_audit()
    assert findings == [], "\n".join(
        f"{f.location}: [{f.rule}] {f.message}" for f in findings
    )


def test_mutation_full_width_gather_fails_audit(devices, monkeypatch):
    """Swap the staged gather in parallel/ring.py for ONE full-width
    jax.lax.all_gather: the audit must go red (S chunked collectives
    became a single full-width one) while the untouched tree passes."""
    import jax

    from matvec_mpi_multiplier_tpu.parallel import ring

    def full_width(a_blk, x_loc, gather_axes, kernel, stages,
                   reduce_axes=None):
        part = kernel(a_blk, x_loc)
        if reduce_axes is not None:
            part = jax.lax.psum(part, reduce_axes)
        return jax.lax.all_gather(part, gather_axes, tiled=True)

    monkeypatch.setattr(ring, "staged_overlap_gather", full_width)
    cfg = AuditConfig("rowwise", "overlap", 2)
    findings = run_hlo_audit(configs=[cfg], check_fingerprints=False)
    assert any(f.rule == "hlo-schedule" for f in findings), findings
    assert any(f.rule == "hlo-census" for f in findings), findings
    # And the same config passes un-mutated.
    monkeypatch.undo()
    assert run_hlo_audit(configs=[cfg], check_fingerprints=False) == []


def test_mutation_unchunked_scatter_fails_audit(devices, monkeypatch):
    """The colwise face: collapsing the S-stage scatter pipeline into one
    full-width psum_scatter breaks the overlap census pin."""
    import jax

    from matvec_mpi_multiplier_tpu.parallel import ring

    def full_width(a_panel, x_seg, axis_name, kernel, stages,
                   step="psum_scatter"):
        return jax.lax.psum_scatter(
            kernel(a_panel, x_seg), axis_name, tiled=True
        )

    monkeypatch.setattr(ring, "staged_overlap_scatter", full_width)
    cfg = AuditConfig("colwise", "overlap", 4)
    findings = run_hlo_audit(configs=[cfg], check_fingerprints=False)
    assert any(
        f.rule == "hlo-schedule" and "S=4" in f.message for f in findings
    ), findings


def test_audit_table_covers_storage_formats():
    """The quantized-storage cells (ISSUE 8): the rowwise format ladder
    plus the compensated pair on colwise and an int8 blockwise cell —
    and every native key keeps its historical no-suffix spelling, so the
    pre-quantization golden entries survive the schema bump."""
    storage_keys = {c.key for c in AUDIT_CONFIGS if c.storage != "native"}
    assert {
        "rowwise|gather|xla|int8", "rowwise|gather|xla|int8c",
        "rowwise|gather|xla|fp8", "colwise|psum_scatter|xla|int8",
        "colwise|psum_scatter|xla|int8c", "blockwise|gather|xla|int8",
    } == storage_keys
    for cfg in AUDIT_CONFIGS:
        if cfg.storage == "native":
            assert "|int8" not in cfg.key and "|fp8" not in cfg.key


def test_mutation_dequant_first_fails_census_gate(devices):
    """The 'silent early-dequant' failure mode: a quantized config whose
    kernel materializes the full dequantized A before the contraction
    stores ¼ the bytes but MOVES all of them. The census gate must flag
    its lowering, and pass the sanctioned tile-wise kernel."""
    from matvec_mpi_multiplier_tpu.ops.quantize import (
        matvec_quantized_dequant_first,
    )
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        early_dequant_findings,
    )

    mesh = make_mesh(AUDIT_DEVICES)
    for cfg in (
        AuditConfig("rowwise", "gather", storage="int8"),
        AuditConfig("colwise", "psum_scatter", storage="int8c"),
    ):
        bad = lower_config(
            cfg, mesh, kernel=matvec_quantized_dequant_first
        )
        findings = early_dequant_findings(cfg, bad, mesh)
        assert any(f.rule == "hlo-early-dequant" for f in findings), (
            f"{cfg.key}: dequant-first lowering not flagged"
        )
        clean = lower_config(cfg, mesh)
        assert early_dequant_findings(cfg, clean, mesh) == []


def test_storage_byte_ceiling_gate_wiring(devices, monkeypatch):
    """An absurdly tight ceiling must surface as hlo-storage-bytes — the
    gate reads the lowered module's parameter bytes, not the builder's
    intent."""
    from matvec_mpi_multiplier_tpu.staticcheck import hlo

    monkeypatch.setitem(hlo.STORAGE_BYTE_CEILING, "int8", 0.01)
    findings = run_hlo_audit(
        configs=[AuditConfig("rowwise", "gather", storage="int8")],
        check_fingerprints=False,
    )
    assert any(f.rule == "hlo-storage-bytes" for f in findings), findings


def test_fingerprint_stability_gate(devices):
    """Same config, two fresh builds → byte-identical lowering hashes (the
    engine-cache silent-recompile guard), and the audit's gate agrees."""
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(AUDIT_DEVICES)
    cfg = AuditConfig("colwise", "overlap", 2)
    assert lowering_fingerprint(lower_config(cfg, mesh)) == \
        lowering_fingerprint(lower_config(cfg, mesh))


def test_engine_cache_records_matching_fingerprints(devices):
    """Two independent engines compiling the same ExecKey must record the
    same lowering fingerprint — the cross-restart identity the AOT cache
    claims (engine/executables.py)."""
    from matvec_mpi_multiplier_tpu import MatvecEngine, make_mesh

    mesh = make_mesh(8)
    a = np.arange(64 * 64, dtype=np.float32).reshape(64, 64) / 64.0

    def fingerprints():
        engine = MatvecEngine(
            a, mesh, strategy="colwise", combine="psum_scatter",
            promote=None,
        )
        engine.warmup(widths=(1,))
        cache = engine._cache
        fps = {key: cache.fingerprint(key) for key in cache._executables}
        engine.close()
        return fps

    first, second = fingerprints(), fingerprints()
    assert first and first == second


def test_golden_roundtrip_and_drift_detection(devices, tmp_path):
    golden = tmp_path / "golden_schedule.json"
    cfg = AuditConfig("colwise", "psum_scatter")
    write_golden(path=golden)
    assert run_hlo_audit(
        golden_path=golden, configs=[cfg], check_fingerprints=False
    ) == []

    # Golden drift: a tampered census pin must surface as hlo-census.
    payload = json.loads(golden.read_text())
    payload["configs"][cfg.key]["census"] = {"all-gather": 3}
    golden.write_text(json.dumps(payload))
    findings = run_hlo_audit(
        golden_path=golden, configs=[cfg], check_fingerprints=False
    )
    assert any(f.rule == "hlo-census" for f in findings), findings

    # A stale pinned config (not in the audit table) is also drift.
    payload["configs"][cfg.key]["census"] = {"reduce-scatter": 1}
    payload["configs"]["colwise|retired_combine|xla"] = {"census": {}}
    golden.write_text(json.dumps(payload))
    findings = run_hlo_audit(
        golden_path=golden, configs=[cfg], check_fingerprints=False
    )
    assert any(
        f.rule == "hlo-golden" and "retired_combine" in f.message
        for f in findings
    ), findings


def test_empty_golden_configs_is_not_a_clean_audit(devices, tmp_path):
    """A golden file whose 'configs' object is empty (bad merge, hand
    edit) must read as every pin missing — never as a silently disabled
    pin layer."""
    golden = tmp_path / "golden_schedule.json"
    golden.write_text(json.dumps({"schema": 1, "configs": {}}))
    findings = run_hlo_audit(
        golden_path=golden,
        configs=[AuditConfig("colwise", "psum")],
        check_fingerprints=False,
    )
    assert any(
        f.rule == "hlo-golden" and "missing from the golden table"
        in f.message
        for f in findings
    ), findings


def test_missing_golden_is_a_finding(devices, tmp_path):
    findings = run_hlo_audit(
        golden_path=tmp_path / "nope.json",
        configs=[AuditConfig("colwise", "psum")],
        check_fingerprints=False,
    )
    assert any(
        f.rule == "hlo-golden" and "--write-golden" in f.message
        for f in findings
    ), findings


# ----------------------------------------------- compiled-artifact memory


def test_donation_lowers_on_engine_recipe(devices):
    """Every audited config's engine-recipe artifact records the RHS
    donation (buffer_donor on CPU, aliasing_output where shapes match);
    lowering WITHOUT donate_argnums reads as 'none' — the audit reads
    the artifact, not the builder's intent."""
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        donation_state,
        lower_engine_artifact,
    )

    mesh = make_mesh(AUDIT_DEVICES)
    cfg = AuditConfig("rowwise", "gather")
    assert donation_state(lower_engine_artifact(cfg, mesh)) in (
        "donated", "aliased",
    )
    assert donation_state(
        lower_engine_artifact(cfg, mesh, donate=())
    ) == "none"
    # A donation recorded on the WRONG argument — donating the resident
    # A, which XLA must never clobber — is not the RHS donation the gate
    # verifies: it must read as 'none', not pass on a whole-module grep.
    assert donation_state(
        lower_engine_artifact(cfg, mesh, donate=(0,))
    ) == "none"


def test_mutation_drop_donation_fails_memory_audit(devices, monkeypatch):
    """The acceptance mutation: removing donate_argnums from the engine
    dispatch recipe turns the memory audit red (hlo-donation) while the
    untouched recipe passes."""
    from matvec_mpi_multiplier_tpu.staticcheck import hlo

    cfg = AuditConfig("colwise", "psum_scatter")
    clean = run_hlo_audit(
        configs=[cfg], check_fingerprints=False, schedule=False,
    )
    assert clean == [], clean
    monkeypatch.setattr(hlo, "ENGINE_DONATE_ARGNUMS", ())
    findings = run_hlo_audit(
        configs=[cfg], check_fingerprints=False, schedule=False,
    )
    assert any(f.rule == "hlo-donation" for f in findings), findings
    # The golden table pins the donation column too: the same mutation
    # also reads as drift against the committed entry.
    assert any(
        f.rule == "hlo-census" and f.severity == "drift" for f in findings
    ), findings


def test_mutation_dequant_first_fails_peak_gate(devices):
    """The liveness-level storage gate: a kernel that materializes the
    dequantized full-width A before the contraction blows through the
    quantized peak ceiling (vs the native counterpart's peak); the
    sanctioned tile-wise kernel stays under it."""
    from matvec_mpi_multiplier_tpu.ops.quantize import (
        matvec_quantized_dequant_first,
    )
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        lower_engine_artifact,
        memory_entry,
        memory_findings,
        native_counterpart,
        peak_buffer_bytes,
    )

    mesh = make_mesh(AUDIT_DEVICES)
    for cfg in (
        AuditConfig("rowwise", "gather", storage="int8"),
        AuditConfig("colwise", "psum_scatter", storage="int8c"),
    ):
        native_peak = peak_buffer_bytes(
            lower_engine_artifact(native_counterpart(cfg), mesh)
        )
        clean = memory_entry(cfg, mesh)
        assert memory_findings(cfg, clean, native_peak) == []
        bad = memory_entry(
            cfg, mesh, kernel=matvec_quantized_dequant_first
        )
        findings = memory_findings(cfg, bad, native_peak)
        assert any(f.rule == "hlo-peak-liveness" for f in findings), (
            cfg.key, bad, native_peak,
        )
        # The dequantized temporary is not subtle: it lands at or above
        # the native peak, nowhere near the quantized ceiling.
        assert bad["peak_bytes"] > 0.95 * native_peak


def test_peak_estimate_quantized_below_native(devices):
    """The liveness story the golden table pins: every quantized
    config's static peak sits below its native counterpart's — the
    storage axis shrinks the high-water mark, not just the resident
    stream."""
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        PEAK_LIVENESS_CEILING,
        lower_engine_artifact,
        memory_entry,
        native_counterpart,
        peak_buffer_bytes,
    )

    mesh = make_mesh(AUDIT_DEVICES)
    cfg = AuditConfig("rowwise", "gather", storage="int8")
    native_peak = peak_buffer_bytes(
        lower_engine_artifact(native_counterpart(cfg), mesh)
    )
    entry = memory_entry(cfg, mesh)
    assert 0 < entry["peak_bytes"] <= (
        PEAK_LIVENESS_CEILING["int8"] * native_peak
    )


def test_shared_artifact_accessor(devices, monkeypatch):
    """The ride-along contract: ExecutableCache compiles and the memory
    audit inspects THE SAME artifact — both route through
    engine.executables.lower_artifact, so they cannot disagree about
    which executable they audited."""
    import numpy as np

    from matvec_mpi_multiplier_tpu import MatvecEngine, make_mesh
    from matvec_mpi_multiplier_tpu.engine import executables
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        lower_engine_artifact,
    )

    calls = []
    real = executables.lower_artifact

    def spy(builder):
        calls.append(builder)
        return real(builder)

    monkeypatch.setattr(executables, "lower_artifact", spy)
    mesh = make_mesh(8)
    # The audit side imports the accessor from the module at call time.
    lower_engine_artifact(AuditConfig("rowwise", "gather"), mesh)
    assert len(calls) == 1
    # The cache side compiles through the same function.
    a = np.arange(64 * 64, dtype=np.float32).reshape(64, 64)
    engine = MatvecEngine(
        a, mesh, strategy="rowwise", combine="gather", promote=None,
    )
    engine.warmup(widths=(1,))
    engine.close()
    assert len(calls) >= 2


# ----------------------------------------------------- CLI verdict/fields


def test_exit_status_distinguishes_failure_classes():
    from matvec_mpi_multiplier_tpu.staticcheck.__main__ import (
        EXIT_CLEAN,
        EXIT_DRIFT,
        EXIT_HLO,
        EXIT_RULES,
        exit_status,
    )
    from matvec_mpi_multiplier_tpu.staticcheck.findings import Finding

    rule = Finding("x.py", 3, "engine-host-sync", "m", marker="sync-ok")
    hlo = Finding("<hlo:k>", 0, "hlo-donation", "m")
    drift = Finding("g.json", 0, "hlo-census", "m", severity="drift")
    assert exit_status([]) == EXIT_CLEAN
    assert exit_status([rule, hlo, drift]) == EXIT_RULES
    assert exit_status([hlo, drift]) == EXIT_HLO
    assert exit_status([drift]) == EXIT_DRIFT


def test_cli_json_findings_carry_rule_severity_marker(tmp_path):
    rel, bad, _clean = RULE_FIXTURES["engine-host-sync"]
    _seed(tmp_path, rel, bad)
    proc = subprocess.run(
        [sys.executable, "-m", "matvec_mpi_multiplier_tpu.staticcheck",
         "--rules", "--root", str(tmp_path), "--json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"], payload
    for f in payload["findings"]:
        assert {"rule", "severity", "marker", "path", "line"} <= set(f)
    sync = [f for f in payload["findings"] if f["rule"] == "engine-host-sync"]
    assert sync and all(
        f["marker"] == "sync-ok" and f["severity"] == "error" for f in sync
    )


def test_cli_lockgraph_flag_runs_only_lock_rules(tmp_path):
    """--lockgraph restricts to rules #13-#15: a seeded host-sync
    violation is invisible to it, a seeded mixed-guard one is not."""
    _seed(tmp_path, RULE_FIXTURES["engine-host-sync"][0],
          RULE_FIXTURES["engine-host-sync"][1])
    proc = subprocess.run(
        [sys.executable, "-m", "matvec_mpi_multiplier_tpu.staticcheck",
         "--lockgraph", "--root", str(tmp_path), "--json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    _seed(tmp_path, RULE_FIXTURES["lock-mixed-guard"][0],
          RULE_FIXTURES["lock-mixed-guard"][1])
    proc = subprocess.run(
        [sys.executable, "-m", "matvec_mpi_multiplier_tpu.staticcheck",
         "--lockgraph", "--root", str(tmp_path), "--json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    assert {f["rule"] for f in payload["findings"]} == {"lock-mixed-guard"}


# ------------------------------------------------------- solver audit


def test_solver_audit_table_covers_ops_and_strategies():
    """Every served op × the three strategy faces (ISSUE 14): the audit
    table is the coverage contract test_data_quality's golden gate pins
    on disk."""
    from matvec_mpi_multiplier_tpu.solvers import SOLVER_OPS
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        SOLVER_AUDIT_CONFIGS,
    )

    ops = {c.op for c in SOLVER_AUDIT_CONFIGS}
    assert ops == set(SOLVER_OPS)
    faces = {(c.strategy, c.combine) for c in SOLVER_AUDIT_CONFIGS}
    assert faces == {
        ("rowwise", "gather"), ("colwise", "psum"),
        ("blockwise", "gather"),
    }
    assert len(SOLVER_AUDIT_CONFIGS) == len(ops) * len(faces)
    # Every config names a matvec counterpart that the main audit table
    # also lowers — the kind-set gate compares against a pinned cell.
    audited = {c.key for c in AUDIT_CONFIGS}
    for scfg in SOLVER_AUDIT_CONFIGS:
        assert scfg.matvec.key in audited, scfg.key


def test_solver_lowering_passes_structural_gates(devices):
    """One real lowering (cg around the colwise psum matvec): the
    compiled program keeps its lax.while on device, uses exactly the
    matvec counterpart's collective kinds, and solver_findings is
    empty."""
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        SOLVER_AUDIT_CONFIGS,
        solver_audit_entry,
        solver_findings,
    )

    mesh = make_mesh(AUDIT_DEVICES)
    scfg = next(
        c for c in SOLVER_AUDIT_CONFIGS
        if c.op == "cg" and c.strategy == "colwise"
    )
    entry = solver_audit_entry(scfg, mesh)
    assert entry["while_ops"] >= 1
    assert "all-reduce" in entry["census"]
    assert solver_findings(scfg, entry, mesh) == []


def test_mutation_host_driven_loop_fails_solver_audit(devices):
    """The failure mode the while-count gate exists for: a 'solver'
    whose iteration is a host-unrolled Python loop of matvecs lowers
    with NO stablehlo.while — k host round-trips per solve, the
    compiles-flat story dead. Feed that real lowering through
    solver_audit_entry and the audit goes red."""
    import jax
    import jax.numpy as jnp

    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        SOLVER_AUDIT_CONFIGS,
        SOLVER_AUDIT_N,
        solver_audit_entry,
        solver_findings,
    )

    mesh = make_mesh(AUDIT_DEVICES)
    # rowwise|gather's census is empty, so the kind-set gate stays green
    # and the while gate alone must catch the unrolled loop.
    scfg = next(
        c for c in SOLVER_AUDIT_CONFIGS
        if c.op == "cg" and c.strategy == "rowwise"
    )

    def unrolled_cg(a, b, rtol, maxiter, p0, p1):
        x = jnp.zeros_like(b)
        r = b
        for _ in range(3):  # fixed-depth Python loop: no lax.while
            x = x + rtol * r
            r = b - a @ x
        return x, jnp.float32(0), jnp.int32(3), rtol, True

    n = SOLVER_AUDIT_N
    import numpy as np
    dt = np.float32
    lowered = jax.jit(unrolled_cg).lower(
        jax.ShapeDtypeStruct((n, n), dt), jax.ShapeDtypeStruct((n,), dt),
        jax.ShapeDtypeStruct((), np.float32),
        jax.ShapeDtypeStruct((), np.int32),
        jax.ShapeDtypeStruct((), np.float32),
        jax.ShapeDtypeStruct((), np.float32),
    )
    entry = solver_audit_entry(scfg, mesh, lowered=lowered)
    assert entry["while_ops"] == 0
    findings = solver_findings(scfg, entry, mesh)
    assert any(f.rule == "hlo-solver-loop" for f in findings), findings


def test_mutation_stray_collective_fails_solver_kind_gate(devices):
    """A collective kind the matvec counterpart never issues (an
    un-staged all-gather smuggled into the loop body) trips
    hlo-solver-schedule — exercised on a fabricated entry so the test
    stays census-level, not lowering-level."""
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        SOLVER_AUDIT_CONFIGS,
        solver_findings,
    )

    mesh = make_mesh(AUDIT_DEVICES)
    scfg = next(
        c for c in SOLVER_AUDIT_CONFIGS
        if c.op == "cg" and c.strategy == "colwise"
    )
    bad = {
        "census": {"all_gather": 6, "psum": 6},
        "payload_bytes": {"all_gather": 1, "psum": 1},
        "while_ops": 1,
    }
    findings = solver_findings(scfg, bad, mesh)
    assert any(f.rule == "hlo-solver-schedule" for f in findings), findings
    assert any("all_gather" in f.message for f in findings)


# ------------------------------------------------- fused-solver audit


def test_fused_solver_audit_covers_both_ops_and_the_quantized_cell():
    """The schema-6 coverage contract: both fixed-recurrence ops across
    both supported strategy faces, plus the int8c-resident cell whose
    zero-dequant pin is the quantized tier's acceptance criterion."""
    from matvec_mpi_multiplier_tpu.ops.pallas_solver import (
        FUSED_SOLVER_OPS,
    )
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        FUSED_SOLVER_AUDIT_CONFIGS,
    )

    assert {c.op for c in FUSED_SOLVER_AUDIT_CONFIGS} == set(
        FUSED_SOLVER_OPS
    )
    faces = {
        (c.strategy, c.combine, c.storage)
        for c in FUSED_SOLVER_AUDIT_CONFIGS
    }
    assert faces == {
        ("rowwise", "gather", "native"),
        ("colwise", "psum", "native"),
        ("colwise", "psum", "int8c"),
    }


def test_fused_solver_trace_passes_structural_gates(devices):
    """One real fused trace per storage face: exactly one while loop,
    exactly ONE pallas_call in its body, exactly the canonical combine's
    single collective hop, and — on the int8c cell — zero full-shard
    dequant converts outside the kernel. This is the tentpole's census
    pin exercised end-to-end, not against the golden file."""
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        FUSED_SOLVER_AUDIT_CONFIGS,
        fused_solver_audit_entry,
        fused_solver_findings,
    )

    mesh = make_mesh(AUDIT_DEVICES)
    for storage in ("native", "int8c"):
        fcfg = next(
            c for c in FUSED_SOLVER_AUDIT_CONFIGS
            if c.op == "cg" and c.strategy == "colwise"
            and c.storage == storage
        )
        entry = fused_solver_audit_entry(fcfg, mesh)
        assert entry["while_ops"] == 1, entry
        assert entry["pallas_calls"] == 1, entry
        assert entry["census"] == {"psum": 1}, entry
        assert entry["lowbit_shard_converts"] == 0, entry
        assert fused_solver_findings(fcfg, entry) == []


def test_mutation_unfused_body_fails_fused_census(devices):
    """Mutation direction 1 (the acceptance criterion's first red): a
    deliberately UNFUSED body — the XLA tier's real lowering traced
    through the fused census — has zero pallas_calls and trips
    hlo-fused-solver. Guards against the tier silently degrading to the
    launch structure it exists to collapse."""
    import jax
    import numpy as np

    from matvec_mpi_multiplier_tpu.models import get_strategy
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.solvers import build_solver
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        FUSED_SOLVER_AUDIT_CONFIGS,
        FUSED_SOLVER_AUDIT_N,
        fused_solver_audit_entry,
        fused_solver_findings,
    )

    mesh = make_mesh(AUDIT_DEVICES)
    fcfg = next(
        c for c in FUSED_SOLVER_AUDIT_CONFIGS
        if c.op == "cg" and c.strategy == "colwise"
        and c.storage == "native"
    )
    n = FUSED_SOLVER_AUDIT_N
    dt = np.dtype(np.float32)
    fn = build_solver(
        fcfg.op, get_strategy(fcfg.strategy), mesh, dtype=dt,
        kernel="xla", combine=fcfg.combine,
    )
    f32 = jax.ShapeDtypeStruct((), np.float32)
    i32 = jax.ShapeDtypeStruct((), np.int32)
    jaxpr = jax.make_jaxpr(fn)(
        jax.ShapeDtypeStruct((n, n), dt), jax.ShapeDtypeStruct((n,), dt),
        f32, i32, f32, f32,
    )
    entry = fused_solver_audit_entry(fcfg, mesh, jaxpr=jaxpr)
    assert entry["pallas_calls"] == 0
    findings = fused_solver_findings(fcfg, entry)
    assert any(
        f.rule == "hlo-fused-solver" and "pallas_call" in f.message
        for f in findings
    ), findings


def test_mutation_stray_collective_fails_fused_census(devices):
    """Mutation direction 2: a second collective smuggled into the fused
    body (census {psum, all_gather}) trips hlo-fused-solver — fabricated
    entry, same precedent as the XLA solver audit's stray-kind test."""
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        FUSED_SOLVER_AUDIT_CONFIGS,
        fused_solver_findings,
    )

    fcfg = next(
        c for c in FUSED_SOLVER_AUDIT_CONFIGS
        if c.op == "cg" and c.strategy == "colwise"
        and c.storage == "native"
    )
    bad = {
        "while_ops": 1, "pallas_calls": 1,
        "census": {"psum": 1, "all_gather": 1},
        "lowbit_shard_converts": 0,
    }
    findings = fused_solver_findings(fcfg, bad)
    assert any(
        f.rule == "hlo-fused-solver" and "stray" in f.message
        for f in findings
    ), findings


def test_mutation_full_shard_dequant_fails_fused_quant_gate(devices):
    """The extended early-dequant gate: an int8c fused entry reporting a
    full-shard low-bit convert outside the kernel trips
    hlo-early-dequant — the quantized fused tier must never materialize
    a dequantized A."""
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        FUSED_SOLVER_AUDIT_CONFIGS,
        fused_solver_findings,
    )

    fcfg = next(
        c for c in FUSED_SOLVER_AUDIT_CONFIGS if c.storage == "int8c"
    )
    bad = {
        "while_ops": 1, "pallas_calls": 1, "census": {"psum": 1},
        "lowbit_shard_converts": 1,
    }
    findings = fused_solver_findings(fcfg, bad)
    assert any(f.rule == "hlo-early-dequant" for f in findings), findings


# ---- the reshard migration audit (hlo-reshard-schedule) ----


def test_reshard_audit_table_covers_every_ordered_pair():
    """The audit must pin every (src, dst) migration the engine can run:
    all 6 ordered pairs over {rowwise, colwise, blockwise}."""
    from matvec_mpi_multiplier_tpu.parallel.reshard import (
        RESHARD_STRATEGIES,
    )
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        RESHARD_AUDIT_CONFIGS,
    )

    pairs = {(c.src, c.dst) for c in RESHARD_AUDIT_CONFIGS}
    expected = {
        (s, d)
        for s in RESHARD_STRATEGIES
        for d in RESHARD_STRATEGIES
        if s != d
    }
    assert pairs == expected
    assert all(c.key == f"reshard|{c.src}|{c.dst}"
               for c in RESHARD_AUDIT_CONFIGS)


def test_reshard_lowerings_pass_structural_gates(devices):
    """Every migration's live lowering satisfies the structural gates
    (minimal census, 1/p payload per step, no gather kinds) without
    consulting the golden table."""
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        RESHARD_AUDIT_CONFIGS,
        reshard_audit_entry,
        reshard_findings,
    )

    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        AUDIT_DTYPE,
        AUDIT_K,
        AUDIT_M,
        dtype_itemsize,
    )

    mesh = make_mesh(len(devices))
    shard_bytes = (
        AUDIT_M * AUDIT_K * dtype_itemsize(AUDIT_DTYPE) // len(devices)
    )
    for rcfg in RESHARD_AUDIT_CONFIGS:
        entry = reshard_audit_entry(rcfg, mesh)
        findings = reshard_findings(rcfg, entry, mesh)
        assert findings == [], (rcfg.key, [f.message for f in findings])
        # The constant-footprint invariant, spelled out: every step's
        # payload is a whole multiple of the device's 1/p local shard.
        assert entry["payload_bytes"], rcfg.key
        assert all(
            b % shard_bytes == 0
            for b in entry["payload_bytes"].values()
        )


def test_mutation_host_gather_fails_reshard_audit(devices, monkeypatch):
    """The acceptance mutation: reroute the migration through a
    gather-and-slice (the on-device signature of a host round trip) —
    the audit must go red on every pair while the untouched build
    passes."""
    from matvec_mpi_multiplier_tpu.parallel import reshard as reshard_mod
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        RESHARD_AUDIT_CONFIGS,
        run_hlo_audit,
    )

    monkeypatch.setattr(reshard_mod, "_MUTATION", "host")
    findings = run_hlo_audit(
        configs=[], reshard_configs=list(RESHARD_AUDIT_CONFIGS),
        check_fingerprints=False,
    )
    red = {f.location for f in findings if f.rule == "hlo-reshard-schedule"}
    assert len(red) == len(RESHARD_AUDIT_CONFIGS), findings
    monkeypatch.undo()
    assert run_hlo_audit(
        configs=[], reshard_configs=list(RESHARD_AUDIT_CONFIGS),
        check_fingerprints=False,
    ) == []


def test_mutation_redundant_collective_fails_reshard_audit(
    devices, monkeypatch
):
    """The second acceptance mutation: a rotate/unrotate ppermute pair —
    value-preserving, so only the census can catch it — must redden the
    audit (the census gate pins the MINIMAL program, not just a correct
    one)."""
    from matvec_mpi_multiplier_tpu.parallel import reshard as reshard_mod
    from matvec_mpi_multiplier_tpu.staticcheck.hlo import (
        RESHARD_AUDIT_CONFIGS,
        run_hlo_audit,
    )

    monkeypatch.setattr(reshard_mod, "_MUTATION", "redundant")
    findings = run_hlo_audit(
        configs=[], reshard_configs=list(RESHARD_AUDIT_CONFIGS),
        check_fingerprints=False,
    )
    red = {f.location for f in findings if f.rule == "hlo-reshard-schedule"}
    assert len(red) == len(RESHARD_AUDIT_CONFIGS), findings
    assert any(
        "redundant" in f.message or "census" in f.message
        for f in findings if f.rule == "hlo-reshard-schedule"
    )


# ------------------------------------------- stale-marker audit (satellite)


def test_stale_marker_is_flagged_and_stale_ok_suppresses(tmp_path):
    """A marker comment whose rule no longer fires anywhere on its lines
    is lint debt — flagged as `stale-marker`; a same-line
    `stale-ok: <reason>` keeps a deliberately anticipatory marker; a
    reasonless `stale-ok:` is itself a finding (the escape hatch cannot
    be silent)."""
    rel = f"{PKG}/engine/seeded.py"
    _seed(
        tmp_path, rel,
        "def dispatch(y):\n"
        "    return y  # sync-ok: nothing here syncs anymore\n",
    )
    found = run_rules(root=tmp_path)
    stale = [f for f in found if f.rule == "stale-marker"]
    assert [(f.path, f.line) for f in stale] == [(rel, 2)], found
    assert "sync-ok" in stale[0].message

    _seed(
        tmp_path, rel,
        "def dispatch(y):\n"
        "    return y  # sync-ok: anticipatory — stale-ok: pinned for the\n",
    )
    found = run_rules(root=tmp_path)
    assert not [f for f in found if f.rule == "stale-marker"], found

    _seed(
        tmp_path, rel,
        "def dispatch(y):\n"
        "    return y  # sync-ok: anticipatory — stale-ok:\n",
    )
    found = run_rules(root=tmp_path)
    assert any(
        f.rule == "marker-missing-reason" and "stale-ok" in f.message
        for f in found
    ), found


def test_live_marker_is_not_stale(tmp_path):
    """The other direction: a marker actually suppressing a finding is
    LIVE coverage, not debt — the engine-host-sync clean twin must not
    trip the stale audit."""
    rel, _bad, clean = RULE_FIXTURES["engine-host-sync"]
    _seed(tmp_path, rel, clean)
    found = run_rules(root=tmp_path)
    assert not [f for f in found if f.rule == "stale-marker"], found


def test_internally_consumed_lock_order_marker_is_live(tmp_path):
    """The subtle liveness class: lock-order-inversion consumes its
    marker INSIDE the graph build (the exempted edge is dropped before
    cycle detection, which also silences the cycle's sibling edges), so
    no raw finding ever reaches the span ledger. The rule's `covered`
    hook must report those consumed spans as live — the marked fixture
    may not be called stale."""
    rel, bad, clean = RULE_FIXTURES["lock-order-inversion"]
    # The edge is recorded at the cross-lock CALL site — the marker goes
    # on that line (the repo's own `with`-line markers cover direct
    # acquisition edges, whose node IS the with statement).
    marked = bad.replace(
        "            self.registry.seeded_charge()\n",
        "            self.registry.seeded_charge()  # lock-order-ok: seeded proven ordering\n",
    )
    assert marked != bad  # the replace matched
    _seed(tmp_path, rel, marked)
    found = run_rules(root=tmp_path)
    assert not [f for f in found if f.rule == "lock-order-inversion"], found
    assert not [f for f in found if f.rule == "stale-marker"], found


def test_repo_tree_has_no_stale_markers():
    """The triage contract on the real tree: every committed marker
    either suppresses a live finding, is internally consumed
    (lock-order edges), or carries a `stale-ok:` reason."""
    found = run_rules()
    assert not [f for f in found if f.rule == "stale-marker"], [
        (f.path, f.line, f.message) for f in found if f.rule == "stale-marker"
    ]


# -------------------------------------------- findings mechanics (satellite)


def test_dedup_collapses_by_path_line_rule():
    from matvec_mpi_multiplier_tpu.staticcheck.findings import (
        Finding,
        dedup,
    )

    a1 = Finding("x.py", 3, "engine-host-sync", "b message")
    a2 = Finding("x.py", 3, "engine-host-sync", "a message")
    other_line = Finding("x.py", 4, "engine-host-sync", "c")
    other_rule = Finding("x.py", 3, "hot-path-blocking-io", "d")
    out = dedup([a1, a2, other_line, other_rule, a1])
    assert len(out) == 3
    kept = {(f.path, f.line, f.rule): f.message for f in out}
    # first-sorted message wins for the collapsed pair
    assert kept[("x.py", 3, "engine-host-sync")] == "a message"


def test_exit_status_keyspace_precedence():
    """keyspace-steady-unwarmed is a hard artifact failure (exit 3, like
    HLO invariants); keyspace-golden alone is drift (exit 4); any AST
    rule finding still dominates both."""
    from matvec_mpi_multiplier_tpu.staticcheck.__main__ import (
        EXIT_DRIFT,
        EXIT_HLO,
        EXIT_RULES,
        exit_status,
    )
    from matvec_mpi_multiplier_tpu.staticcheck.findings import Finding
    from matvec_mpi_multiplier_tpu.staticcheck.keyspace import GOLDEN_REL

    hard = Finding(GOLDEN_REL, 0, "keyspace-steady-unwarmed", "m")
    drift = Finding(GOLDEN_REL, 0, "keyspace-golden", "m")
    rule = Finding("x.py", 3, "engine-host-sync", "m", marker="sync-ok")
    assert drift.severity == "drift"  # DRIFT_RULES owns the severity
    assert hard.severity == "error"
    assert exit_status([drift]) == EXIT_DRIFT
    assert exit_status([hard, drift]) == EXIT_HLO
    assert exit_status([rule, hard, drift]) == EXIT_RULES


@pytest.mark.parametrize("rule", sorted(RULE_FIXTURES))
def test_findings_round_trip_rule_severity_marker(rule, tmp_path):
    """The property the --json consumers (CI artifact, the workflow's
    jq gates) rely on: every rule's finding serializes with its
    registry-declared rule id and marker, severity 'error', and
    survives a JSON round trip field-for-field."""
    from matvec_mpi_multiplier_tpu.staticcheck.findings import Finding

    rel, bad, _clean = RULE_FIXTURES[rule]
    _seed(tmp_path, rel, bad)
    found = [f for f in run_rules(root=tmp_path, rules=[rule])
             if f.rule == rule]
    assert found
    for f in found:
        payload = json.loads(json.dumps(f.as_dict()))
        assert payload["rule"] == rule
        assert payload["severity"] == "error"
        assert payload["marker"] == RULES[rule].marker
        assert payload["path"] == rel and payload["line"] >= 1
        assert Finding(**payload) == f


def test_source_file_cache_shares_and_invalidates(tmp_path):
    """One parse per content: repeated corpus access returns the SAME
    SourceFile object, and an on-disk edit (fixture/mutation flows)
    invalidates by content — never served stale."""
    from matvec_mpi_multiplier_tpu.staticcheck.corpus import source_file

    rel = f"{PKG}/ops/seeded.py"
    _seed(tmp_path, rel, "A = 1\n")
    path = tmp_path / rel
    first = source_file(path, tmp_path)
    assert source_file(path, tmp_path) is first
    _seed(tmp_path, rel, "A = 2\n")
    fresh = source_file(path, tmp_path)
    assert fresh is not first and fresh.text == "A = 2\n"


def test_dataflow_cache_invalidates_on_edit(tmp_path):
    """The dataflow engine's per-file cache keys on content: editing a
    clean file into a violating one (same path, same run pattern as the
    fixture tests) must produce the finding — no stale verdicts."""
    rel, bad, clean = RULE_FIXTURES["traced-python-branch"]
    _seed(tmp_path, rel, clean)
    assert run_rules(root=tmp_path, rules=["traced-python-branch"]) == []
    _seed(tmp_path, rel, bad)
    found = run_rules(root=tmp_path, rules=["traced-python-branch"])
    assert any(f.rule == "traced-python-branch" for f in found), found


# ------------------------------------------- keyspace audit (layer 3)


def test_keyspace_audit_green_on_untouched_tree():
    """The committed golden matches the enumerator and every pinned
    config satisfies the compile budget — the `--keyspace` CLI tier."""
    from matvec_mpi_multiplier_tpu.staticcheck.keyspace import (
        run_keyspace_audit,
    )

    assert run_keyspace_audit(REPO) == []


def test_keyspace_budget_proves_steady_subset_of_warmup():
    """The static compiles_steady == 0 proof, config by config: steady
    routing never reaches a key warmup does not compile, and the budget
    record says so."""
    from matvec_mpi_multiplier_tpu.staticcheck.keyspace import (
        KEYSPACE_CONFIGS,
        enumerate_keyspace,
    )

    assert len(KEYSPACE_CONFIGS) >= 8
    for cfg in KEYSPACE_CONFIGS:
        space = enumerate_keyspace(cfg)
        assert set(space.steady) <= set(space.warmup), cfg.name
        assert space.budget["steady_beyond_warmup"] == 0, cfg.name
        assert space.budget["warmup"] == len(space.warmup)
        assert space.budget["total"] == len(
            set(space.warmup) | set(space.steady)
            | set(space.fault_only) | set(space.rollover)
        )
        # The classes partition: fault/rollover never duplicate a
        # warm/steady key (a key is classified by its FIRST compile).
        assert not set(space.fault_only) & set(space.warmup)
        assert not set(space.rollover) & set(space.warmup)


def test_keyspace_golden_drift_detected_on_widened_surface():
    """A silently widened keyspace (one extra warm key) and a missing
    golden both surface as keyspace-golden findings — drift severity,
    never a hard error."""
    import copy

    from matvec_mpi_multiplier_tpu.staticcheck.keyspace import (
        audit_table,
        keyspace_table,
        load_golden,
    )

    table = keyspace_table()
    golden = load_golden(REPO)
    assert golden is not None
    assert audit_table(table, golden) == []

    widened = copy.deepcopy(table)
    name = sorted(widened["configs"])[0]
    widened["configs"][name]["warmup"].append(
        "gemm:rowwise:pallas:none:512:float64"
    )
    findings = audit_table(widened, golden)
    assert any(
        f.rule == "keyspace-golden" and name in f.message
        and f.severity == "drift"
        for f in findings
    ), findings

    findings = audit_table(table, None)
    assert [f.rule for f in findings] == ["keyspace-golden"]


def test_keyspace_mutation_unwarmed_steady_key_is_hard_red(monkeypatch):
    """The budget gate bites: narrow the warmup enumeration by one
    bucket (a warmup() that stops covering the ladder) and the audit
    must go hard red (keyspace-steady-unwarmed) AND --write-golden must
    refuse to bless the broken invariant."""
    from matvec_mpi_multiplier_tpu.staticcheck import keyspace as ks

    real = ks._warm_buckets

    def narrowed(cfg):
        buckets = real(cfg)
        return set(sorted(buckets)[:-1]) if buckets else buckets

    monkeypatch.setattr(ks, "_warm_buckets", narrowed)
    findings = ks.audit_table(ks.keyspace_table(), ks.load_golden(REPO))
    hard = [f for f in findings if f.rule == "keyspace-steady-unwarmed"]
    assert hard, findings
    assert all(f.severity == "error" for f in hard)
    with pytest.raises(ValueError, match="refusing to bless"):
        ks.write_golden_keyspace()
    monkeypatch.undo()
    assert ks.run_keyspace_audit(REPO) == []


def test_keyspace_cross_check_engine_ground_truth(devices):
    """The symbolic enumeration against the engine's own key
    constructors (MatvecEngine.exec_keyspace): same warmup, steady and
    fault-only label sets for a plain GEMM-ladder config and for the
    solver-serving config — the static proof is about the REAL key
    mint, not a parallel re-derivation."""
    from matvec_mpi_multiplier_tpu.engine.core import MatvecEngine
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.solvers import SOLVER_OPS
    from matvec_mpi_multiplier_tpu.staticcheck.keyspace import (
        ServeConfig,
        enumerate_keyspace,
    )

    mesh = make_mesh(len(devices))
    a = np.arange(64 * 64, dtype=np.float32).reshape(64, 64)

    space = enumerate_keyspace(
        ServeConfig(name="x", strategy="rowwise", promote=8, max_bucket=32)
    )
    engine = MatvecEngine(
        a, mesh, strategy="rowwise", promote=8, max_bucket=32,
    )
    try:
        live = engine.exec_keyspace()
        assert live["warmup"] == list(space.warmup)
        assert live["steady"] == list(space.steady)
        assert live["fault_only"] == list(space.fault_only)
    finally:
        engine.close()

    space = enumerate_keyspace(ServeConfig(
        name="x", strategy="rowwise", promote=None,
        solver_ops=tuple(SOLVER_OPS),
    ))
    engine = MatvecEngine(a, mesh, strategy="rowwise", promote=None)
    try:
        live = engine.exec_keyspace(solver_ops=tuple(SOLVER_OPS))
        assert live["warmup"] == list(space.warmup)
        assert live["steady"] == list(space.steady)
        assert live["fault_only"] == list(space.fault_only)
    finally:
        engine.close()


def test_keyspace_covers_live_compile_set(devices):
    """Dynamic containment: after warmup plus steady traffic (a
    remainder width and a full bucket), every key the executable cache
    actually compiled is inside the enumerated warmup set — the compiled
    reality never escapes the static surface."""
    from matvec_mpi_multiplier_tpu.engine.core import MatvecEngine
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.staticcheck.keyspace import (
        ServeConfig,
        enumerate_keyspace,
    )

    space = enumerate_keyspace(
        ServeConfig(name="x", strategy="rowwise", promote=8, max_bucket=32)
    )
    mesh = make_mesh(len(devices))
    a = np.arange(64 * 64, dtype=np.float32).reshape(64, 64)
    engine = MatvecEngine(
        a, mesh, strategy="rowwise", promote=8, max_bucket=32,
    )
    try:
        engine.warmup()
        engine.submit(np.ones((64, 5), np.float32)).result()
        engine.submit(np.ones((64, 20), np.float32)).result()
        compiled = {k.label() for k in engine._cache.keys()}
    finally:
        engine.close()
    assert compiled <= set(space.warmup), compiled - set(space.warmup)


# ------------------------------------------------ doc-drift gate (satellite)


def test_rule_index_doc_matches_registry():
    """docs/STATIC_ANALYSIS.md's rule-index table is test-checked
    against the live registry in BOTH directions: every registered rule
    has a row, no row names a dead rule, and each row's marker and
    scope cells are the registry's own strings (MARKERS / scope_label)
    — renaming, re-scoping or re-markering a rule without the doc is a
    failure."""
    import re

    from matvec_mpi_multiplier_tpu.staticcheck import MARKERS
    from matvec_mpi_multiplier_tpu.staticcheck.rules import scope_label

    doc = (REPO / "docs" / "STATIC_ANALYSIS.md").read_text()
    rows = {}
    for line in doc.splitlines():
        m = re.match(r"^\| `([a-z0-9-]+)` \|", line)
        if not m or m.group(1) not in RULES:
            continue
        cells = [c.strip() for c in line.split("|")]
        assert len(cells) == 6, f"malformed rule-index row: {line!r}"
        rows[m.group(1)] = (cells[2], cells[3])
    assert set(rows) == set(RULES), (
        "rule-index table out of sync with the registry: "
        f"doc-only={sorted(set(rows) - set(RULES))}, "
        f"registry-only={sorted(set(RULES) - set(rows))}"
    )
    for rule, (marker_cell, scope_cell) in rows.items():
        marker = RULES[rule].marker
        want_marker = f"`{marker}`" if marker else "—"
        assert marker_cell == want_marker, (rule, marker_cell, want_marker)
        assert scope_cell == f"`{scope_label(rule)}`", (rule, scope_cell)
    # The marker registry itself backs the doc's contract section.
    assert MARKERS == {
        r.marker: r.name for r in RULES.values() if r.marker
    }

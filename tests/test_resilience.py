"""Resilience-subsystem tests (resilience/ + engine/scheduler hardening).

Layers, bottom-up:

* **FaultPlan / parse grammar** — deterministic seeded injection: the
  same plan over the same event sequence makes identical decisions.
* **RetryPolicy / CircuitBreaker** — backoff determinism and the
  closed→open→half-open state machine on a fake clock.
* **Engine integration** — retries recover transient faults; the
  degradation ladder + per-ExecKey breaker reroutes a failing config and
  half-open-probes back; RESOURCE_EXHAUSTED shrinks the bucket ladder;
  the NaN/Inf integrity gate refuses corrupt results; ``health()`` and
  the ``resil_*`` obs counters expose all of it.
* **Chaos acceptance** (``chaos`` marker — deterministic and fast, part
  of tier-1): the ISSUE 7 criteria — a 200-request coalesced trace with
  ≥5 %% poisoned dispatches completes with every non-poisoned request
  bitwise-correct (batch bisection), and an ExecKey-targeted
  compile-failure plan demonstrably opens and half-open-recovers the
  breaker with the downgrade visible in ``engine.health()``.
"""

import numpy as np
import pytest

from matvec_mpi_multiplier_tpu import make_mesh
from matvec_mpi_multiplier_tpu.engine import (
    ArrivalWindowScheduler,
    MatvecEngine,
)
from matvec_mpi_multiplier_tpu.resilience import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CircuitBreaker,
    CompileFaultError,
    DeviceFaultError,
    FaultPlan,
    FaultSpec,
    ResiliencePolicy,
    ResourceExhaustedError,
    RetryPolicy,
    classify_failure,
    parse_fault_spec,
)
from matvec_mpi_multiplier_tpu.utils.errors import ConfigError


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def quiet_policy(**kwargs):
    """A ResiliencePolicy that never really sleeps (tests)."""
    kwargs.setdefault("retry", RetryPolicy(max_attempts=3))
    kwargs.setdefault("sleep", lambda s: None)
    return ResiliencePolicy(**kwargs)


def make_engine(rng, m=64, k=64, **kwargs):
    a = rng.uniform(0, 10, (m, k)).astype("float32")
    kwargs.setdefault("promote", 2)
    kwargs.setdefault("max_bucket", 8)
    return a, MatvecEngine(a, make_mesh(8), strategy="rowwise", **kwargs)


# ------------------------------------------------------------- fault plan


def test_fault_plan_is_deterministic_per_seed():
    def run(seed):
        plan = FaultPlan(
            [FaultSpec(site="dispatch", kind="device_error", p=0.3)],
            seed=seed,
        )
        fired = []
        for i in range(100):
            action = plan.check("dispatch", "matvec:rowwise:xla:default:1:f")
            fired.append(action is not None)
        return fired

    first = run(7)
    assert first == run(7)  # exact replay
    assert first != run(8)  # and actually seed-dependent
    assert 10 < sum(first) < 60  # p=0.3ish, not degenerate


def test_fault_plan_times_after_and_key_scoping():
    plan = FaultPlan([
        FaultSpec(site="dispatch", kind="device_error", key="*gemm*",
                  times=2, after=1),
    ])
    label = "gemm:rowwise:xla:default:8:float32"
    assert plan.check("dispatch", "matvec:rowwise:xla:default:1:f") is None
    assert plan.check("compile", label) is None  # wrong site
    assert plan.check("dispatch", label) is None  # after=1 spares the first
    assert plan.check("dispatch", label) is not None
    assert plan.check("dispatch", label) is not None  # times=2 exhausted...
    assert plan.check("dispatch", label) is None
    summary = plan.summary()["specs"][0]
    assert summary["matched"] == 4 and summary["injected"] == 2


def test_fault_plan_poison_scoping_matches_payload():
    poison = 1e30
    plan = FaultPlan([
        FaultSpec(site="dispatch", kind="device_error", poison=poison),
    ])
    clean = np.ones((4, 2), np.float32)
    assert plan.check("dispatch", "k", block=clean) is None
    bad = clean.copy()
    bad[0, 1] = np.float32(poison)
    action = plan.check("dispatch", "k", block=bad)
    assert action is not None
    assert isinstance(action.error, DeviceFaultError)
    assert action.error.retryable is False  # poisoned => persistent


def test_fault_plan_disarm_spares_events():
    plan = FaultPlan([FaultSpec(site="dispatch", kind="device_error")])
    plan.disarm()
    assert plan.check("dispatch", "k") is None
    assert plan.summary()["specs"][0]["matched"] == 0  # not even tallied
    plan.arm()
    assert plan.check("dispatch", "k") is not None


def test_fault_kinds_map_to_taxonomy_and_actions():
    def one(spec, site="dispatch"):
        return FaultPlan([spec]).check(site, "k")

    assert isinstance(
        one(FaultSpec(site="compile", kind="compile_error"),
            site="compile").error,
        CompileFaultError,
    )
    assert isinstance(
        one(FaultSpec(site="dispatch", kind="resource_exhausted")).error,
        ResourceExhaustedError,
    )
    nan_action = one(FaultSpec(site="dispatch", kind="nan"))
    assert nan_action.corrupt and nan_action.error is None
    lat = one(FaultSpec(site="dispatch", kind="latency", latency_ms=3.0))
    assert lat.latency_ms == 3.0 and not lat.corrupt and lat.error is None


def test_fault_plan_first_matching_spec_wins():
    plan = FaultPlan([
        FaultSpec(site="dispatch", kind="resource_exhausted", times=1),
        FaultSpec(site="dispatch", kind="nan"),
    ])
    assert isinstance(plan.check("dispatch", "k").error,
                      ResourceExhaustedError)
    # spec 0 exhausted: the nan spec (fresh ordinals) takes over
    assert plan.check("dispatch", "k").corrupt


def test_parse_fault_spec_grammar_round_trip():
    plan = parse_fault_spec(
        "dispatch:device_error:p=0.05;"
        "compile:compile_error:key=*psum_scatter*,times=4;"
        "dispatch:latency:latency_ms=5,p=0.1,after=2,retryable=0",
        seed=9,
    )
    assert plan.seed == 9
    d, c, l = plan.specs
    assert d.p == 0.05 and d.key == "*"
    assert c.key == "*psum_scatter*" and c.times == 4
    assert l.latency_ms == 5.0 and l.after == 2 and l.retryable is False


@pytest.mark.parametrize("bad", [
    "nonsense",                      # no site:kind
    "dispatch:explode",              # unknown kind
    "teleport:device_error",         # unknown site
    "dispatch:device_error:p=2.0",   # probability out of range
    "dispatch:device_error:frobnicate=1",  # unknown field
    "dispatch:latency",              # latency without latency_ms
    ";;",                            # empty
])
def test_parse_fault_spec_rejects_malformed(bad):
    with pytest.raises(ConfigError):
        parse_fault_spec(bad)


def test_classify_failure_reads_real_backend_messages():
    assert classify_failure(RuntimeError("RESOURCE_EXHAUSTED: oom")) == (
        False, True,
    )
    assert classify_failure(RuntimeError("UNAVAILABLE: link flap")) == (
        True, False,
    )
    assert classify_failure(ValueError("shape mismatch")) == (False, False)


# ---------------------------------------------------------- retry policy


def test_retry_delay_deterministic_growing_and_capped():
    r = RetryPolicy(backoff_ms=1.0, multiplier=2.0, max_backoff_ms=4.0,
                    jitter=0.5, seed=3)
    d1, d2, d3 = (r.delay_s(0, a) for a in (1, 2, 3))
    assert d1 == r.delay_s(0, 1)  # deterministic
    assert d1 < d2  # growing
    assert d3 <= 4.0 / 1e3  # capped
    assert r.delay_s(0, 1) != r.delay_s(1, 1)  # jitter varies per serial


# ------------------------------------------------------- circuit breaker


def test_breaker_state_machine_and_single_probe():
    clock = FakeClock()
    opens, closes = [], []
    br = CircuitBreaker(
        failure_threshold=3, reset_timeout_s=10.0, clock=clock,
        on_open=lambda: opens.append(clock.t),
        on_close=lambda: closes.append(clock.t),
    )
    assert br.state == BREAKER_CLOSED
    for _ in range(2):
        assert br.allow()
        br.record_failure()
    assert br.state == BREAKER_CLOSED  # below threshold
    assert br.allow()
    br.record_failure()
    assert br.state == BREAKER_OPEN and len(opens) == 1
    assert not br.allow()  # pre-cooldown: refuse
    clock.advance(10.0)
    assert br.state == BREAKER_HALF_OPEN
    assert br.allow()       # the one probe
    assert not br.allow()   # a second caller must wait the probe out
    br.record_failure()     # failed probe: back to open, timer reset
    assert br.state == BREAKER_OPEN and len(opens) == 2
    assert not br.allow()
    clock.advance(10.0)
    assert br.allow()
    br.record_success()     # successful probe: recovered
    assert br.state == BREAKER_CLOSED and len(closes) == 1
    snap = br.snapshot()
    assert snap["failures_total"] == 4 and snap["opens_total"] == 2


def test_breaker_success_resets_consecutive_failures():
    br = CircuitBreaker(failure_threshold=2, clock=FakeClock())
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == BREAKER_CLOSED  # never two in a row


def test_breaker_inconclusive_releases_probe_without_transition():
    """A payload-caused failure is inconclusive about the CONFIG: it
    must not advance the failure count while closed, and a half-open
    probe that hit one must release the probe slot so the next request
    can probe again (not transition back to open)."""
    clock = FakeClock()
    br = CircuitBreaker(
        failure_threshold=2, reset_timeout_s=10.0, clock=clock
    )
    for _ in range(5):
        br.record_inconclusive()
    assert br.state == BREAKER_CLOSED
    assert br.snapshot()["consecutive_failures"] == 0
    br.record_failure()
    br.record_failure()  # real failures still open it
    assert br.state == BREAKER_OPEN
    clock.advance(10.0)
    assert br.allow()        # the one half-open probe
    br.record_inconclusive()  # probe drew a poisoned request
    assert br.state == BREAKER_HALF_OPEN  # not re-opened
    assert br.allow()        # slot released: next caller may probe
    br.record_success()
    assert br.state == BREAKER_CLOSED


# ------------------------------------------------- engine: fault hooks


def test_transient_dispatch_fault_retries_to_success(devices, rng):
    plan = FaultPlan(
        [FaultSpec(site="dispatch", kind="device_error", times=2)]
    )
    a, eng = make_engine(rng, fault_plan=plan, resilience=quiet_policy())
    x = rng.uniform(0, 10, (64,)).astype(np.float32)
    np.testing.assert_allclose(eng(x), a @ x, rtol=1e-5)
    h = eng.health()
    assert h["counters"]["retries"] == 2
    assert h["counters"]["faults_injected"] == 2
    assert h["counters"]["downgrades"] == 0  # same level recovered
    assert h["counters"]["dispatch_failures"] == 0


def test_retries_exhausted_raises_and_counts_dispatch_failure(devices, rng):
    plan = FaultPlan([FaultSpec(site="dispatch", kind="device_error")])
    a, eng = make_engine(
        rng, fault_plan=plan,
        resilience=quiet_policy(retry=RetryPolicy(max_attempts=2)),
    )
    x = rng.uniform(0, 10, (64,)).astype(np.float32)
    with pytest.raises(DeviceFaultError):
        eng.submit(x)
    h = eng.health()
    # preferred == safe config for the default engine: a one-level ladder
    assert h["counters"]["dispatch_failures"] == 1
    assert eng.tracer.traces()[-1]["status"] == "dispatch_failed"


def test_fault_plan_without_policy_propagates_raw(devices, rng):
    plan = FaultPlan([FaultSpec(site="dispatch", kind="device_error")])
    a, eng = make_engine(rng, fault_plan=plan)
    with pytest.raises(DeviceFaultError):
        eng.submit(rng.uniform(0, 10, (64,)).astype(np.float32))
    assert eng.health()["counters"]["retries"] == 0


def test_latency_fault_stalls_but_serves(devices, rng):
    plan = FaultPlan(
        [FaultSpec(site="dispatch", kind="latency", latency_ms=1.0, times=1)]
    )
    a, eng = make_engine(rng, fault_plan=plan, resilience=quiet_policy())
    x = rng.uniform(0, 10, (64,)).astype(np.float32)
    np.testing.assert_allclose(eng(x), a @ x, rtol=1e-5)
    assert eng.health()["counters"]["faults_injected"] == 1


# ------------------------------------- engine: ladder, breaker, shrink


def test_compile_fault_degrades_then_half_open_recovers(devices, rng):
    """The acceptance breaker story: an ExecKey-targeted compile-failure
    plan on an exotic combine opens the breaker (requests keep succeeding
    through the safe fallback — graceful degradation, zero client-visible
    failures), and once the plan exhausts, the half-open probe restores
    the preferred config."""
    clock = FakeClock()
    plan = FaultPlan([
        FaultSpec(site="compile", kind="compile_error",
                  key="*psum_scatter*", times=4),
    ])
    pol = quiet_policy(
        retry=RetryPolicy(max_attempts=2),
        breaker_failure_threshold=3, breaker_reset_s=5.0, clock=clock,
    )
    a = rng.uniform(0, 10, (64, 64)).astype(np.float32)
    eng = MatvecEngine(
        a, make_mesh(8), strategy="colwise", combine="psum_scatter",
        max_bucket=8, promote=None, fault_plan=plan, resilience=pol,
    )
    x = rng.uniform(0, 10, (64,)).astype(np.float32)

    # 3 failures open the breaker; every request still serves (degraded).
    for _ in range(4):
        np.testing.assert_allclose(eng(x), a @ x, rtol=1e-5)
    h = eng.health()
    pref = [l for l in h["breakers"] if "psum_scatter" in l]
    assert pref and h["breakers"][pref[0]]["state"] == BREAKER_OPEN
    assert h["degraded"] == {
        "matvec:colwise:xla:psum_scatter:1:float32":
            "matvec:colwise:xla:default:1:float32",
    }
    assert h["counters"]["breaker_opens"] == 1
    assert h["counters"]["downgrades"] == 4
    assert h["counters"]["dispatch_failures"] == 0  # nobody failed

    # Cooldown -> probe hits injected fault #4 -> reopens.
    clock.advance(6.0)
    np.testing.assert_allclose(eng(x), a @ x, rtol=1e-5)
    h = eng.health()
    assert h["breakers"][pref[0]]["state"] == BREAKER_OPEN
    assert h["counters"]["breaker_opens"] == 2

    # Second cooldown -> plan exhausted -> probe compiles -> recovery.
    clock.advance(6.0)
    np.testing.assert_allclose(eng(x), a @ x, rtol=1e-5)
    h = eng.health()
    assert h["breakers"][pref[0]]["state"] == BREAKER_CLOSED
    assert h["counters"]["recoveries"] == 1
    assert h["degraded"] == {}  # preferred config restored
    # the obs registry carries the same story (one source of truth)
    counters = eng.metrics.snapshot()["counters"]
    assert counters["resil_breaker_opens_total"] == 2
    assert counters["resil_recoveries_total"] == 1
    assert counters["resil_downgrades_total"] == h["counters"]["downgrades"]


def test_open_breaker_skips_preferred_attempts(devices, rng):
    """While open, the failing config is not even attempted — the fault
    plan sees no new compile events until the half-open probe."""
    clock = FakeClock()
    plan = FaultPlan([
        FaultSpec(site="compile", kind="compile_error",
                  key="*psum_scatter*"),
    ])
    pol = quiet_policy(
        retry=RetryPolicy(max_attempts=1),
        breaker_failure_threshold=2, breaker_reset_s=30.0, clock=clock,
    )
    a = rng.uniform(0, 10, (64, 64)).astype(np.float32)
    eng = MatvecEngine(
        a, make_mesh(8), strategy="colwise", combine="psum_scatter",
        max_bucket=8, promote=None, fault_plan=plan, resilience=pol,
    )
    x = rng.uniform(0, 10, (64,)).astype(np.float32)
    for _ in range(6):
        eng(x)
    # 2 attempts opened the breaker; the other 4 went straight to safe.
    assert eng.health()["fault_injection"]["specs"][0]["injected"] == 2


def test_resource_exhausted_shrinks_bucket_ladder(devices, rng):
    plan = FaultPlan([
        FaultSpec(site="dispatch", kind="resource_exhausted",
                  key="gemm:*:8:*"),
    ])
    a, eng = make_engine(rng, fault_plan=plan, resilience=quiet_policy())
    blk = rng.uniform(0, 10, (64, 8)).astype(np.float32)
    np.testing.assert_allclose(eng(blk), a @ blk, rtol=1e-5)
    h = eng.health()
    assert h["counters"]["downgrades"] >= 1  # the shrink
    assert h["counters"]["dispatch_failures"] == 0
    # the 8-wide bucket is marked failing; the halves served
    assert any("8" in label for label in h["breakers"])


def test_gemm_ladder_falls_to_per_column_gemv(devices, rng):
    """Every GEMM level failing degrades the promotion decision itself:
    the block serves as per-column GEMV dispatches."""
    plan = FaultPlan([
        FaultSpec(site="dispatch", kind="device_error", key="gemm:*",
                  retryable=False),
    ])
    a, eng = make_engine(rng, fault_plan=plan, resilience=quiet_policy())
    blk = rng.uniform(0, 10, (64, 4)).astype(np.float32)
    np.testing.assert_allclose(eng(blk), a @ blk, rtol=1e-5)
    h = eng.health()
    assert h["counters"]["downgrades"] >= 1
    assert h["counters"]["dispatch_failures"] == 0
    # per-column results must be the matvec path's exact outputs
    solo = np.stack([eng(blk[:, j]) for j in range(4)], axis=1)
    np.testing.assert_array_equal(eng(blk), solo)


def test_poisoned_payloads_do_not_open_breaker(devices, rng):
    """A client streaming poisoned requests must not become a
    performance-degradation vector for everyone else: payload faults are
    exempt from config-health accounting, so the breaker stays closed
    and healthy traffic keeps riding the preferred config."""
    poison = 1e30
    plan = FaultPlan([
        FaultSpec(site="dispatch", kind="device_error", poison=poison),
    ])
    pol = quiet_policy(breaker_failure_threshold=3)
    a, eng = make_engine(rng, fault_plan=plan, resilience=pol)
    bad = rng.uniform(0, 10, (64,)).astype(np.float32)
    bad[0] = np.float32(poison)
    for _ in range(5):  # well past the 3-failure threshold
        with pytest.raises(DeviceFaultError):
            eng(bad)
    h = eng.health()
    for label, snap in h["breakers"].items():
        assert snap["state"] == BREAKER_CLOSED, label
        assert snap["consecutive_failures"] == 0, label
    # Healthy traffic is untouched: preferred config, no downgrade.
    good = rng.uniform(0, 10, (64,)).astype(np.float32)
    np.testing.assert_allclose(eng(good), a @ good, rtol=1e-5)
    assert h["degraded"] == {}
    assert eng.health()["counters"]["downgrades"] == 0


def test_health_is_safe_under_degradation_churn(devices, rng):
    """health() snapshots the degraded map while dispatch threads flip
    configs between degraded and recovered — the copy must be taken
    under the same lock the ladder mutates under (a bare dict() copy
    can raise RuntimeError mid-iteration)."""
    import threading

    plan = FaultPlan([
        # Scoped to the preferred (ring-gather) config only, 50/50: each
        # request either degrades to the safe tier (map insert) or serves
        # preferred (map pop) — sustained churn on _degraded.
        FaultSpec(site="dispatch", kind="device_error", key="*:ring:*",
                  p=0.5, retryable=False),
    ])
    pol = quiet_policy(
        retry=RetryPolicy(max_attempts=1),
        breaker_failure_threshold=10_000,  # keep the preferred level live
    )
    a = rng.uniform(0, 10, (64, 64)).astype(np.float32)
    eng = MatvecEngine(
        a, make_mesh(8), strategy="rowwise", combine="ring",
        max_bucket=8, promote=None, fault_plan=plan, resilience=pol,
    )
    errors: list[BaseException] = []
    stop = threading.Event()

    def poll():
        try:
            while not stop.is_set():
                eng.health()
        except BaseException as e:
            errors.append(e)

    t = threading.Thread(target=poll)
    t.start()
    try:
        x = rng.uniform(0, 10, (64,)).astype(np.float32)
        for _ in range(60):
            np.testing.assert_allclose(eng(x), a @ x, rtol=1e-5)
    finally:
        stop.set()
        t.join(timeout=10.0)
    assert not errors, errors
    assert eng.health()["counters"]["downgrades"] > 0  # churn was real


# ------------------------------------------- integrity gate & corruption


def test_nan_fault_with_gate_refuses_then_recovers(devices, rng):
    from matvec_mpi_multiplier_tpu.resilience import ResultIntegrityError

    plan = FaultPlan([FaultSpec(site="dispatch", kind="nan", times=1)])
    a, eng = make_engine(rng, fault_plan=plan, integrity_gate=True)
    x = rng.uniform(0, 10, (64,)).astype(np.float32)
    with pytest.raises(ResultIntegrityError):
        eng(x)
    assert eng.tracer.traces()[-1]["status"] == "integrity_failed"
    np.testing.assert_allclose(eng(x), a @ x, rtol=1e-5)
    assert eng.health()["counters"]["integrity_failures"] == 1


def test_integrity_refusal_is_cached_on_the_future(devices, rng):
    """A gate refusal behaves like any other future failure: repeated
    result() raises the SAME error without re-counting the refusal, and
    exception() reports it — on both the engine future and the
    scheduler's per-slice gate."""
    from matvec_mpi_multiplier_tpu.resilience import ResultIntegrityError

    plan = FaultPlan([FaultSpec(site="dispatch", kind="nan", times=1)])
    a, eng = make_engine(rng, fault_plan=plan, integrity_gate=True)
    x = rng.uniform(0, 10, (64,)).astype(np.float32)
    fut = eng.submit(x)
    with pytest.raises(ResultIntegrityError):
        fut.result()
    with pytest.raises(ResultIntegrityError):
        fut.result()
    assert isinstance(fut.exception(), ResultIntegrityError)
    assert eng.health()["counters"]["integrity_failures"] == 1
    eng.close()

    # Per-slice gate on a coalesced future: same caching contract.
    plan = FaultPlan([FaultSpec(site="dispatch", kind="nan", times=1)])
    a, eng = make_engine(rng, fault_plan=plan, integrity_gate=True)
    sched = ArrivalWindowScheduler(eng, auto_flush=False, window_ms=50.0)
    futs = [sched.submit(x) for _ in range(2)]
    sched.flush()
    with pytest.raises(ResultIntegrityError):
        futs[0].result(timeout=10)
    with pytest.raises(ResultIntegrityError):
        futs[0].result(timeout=10)
    assert isinstance(futs[0].exception(), ResultIntegrityError)
    np.testing.assert_allclose(
        futs[1].result(timeout=10), a @ x, rtol=1e-5
    )
    assert eng.health()["counters"]["integrity_failures"] == 1
    sched.close()
    eng.close()


def test_nan_fault_without_gate_serves_corrupt_data(devices, rng):
    """The gate is what stands between corruption and the caller: off,
    the NaN goes through — the documented trade the flag controls."""
    plan = FaultPlan([FaultSpec(site="dispatch", kind="nan", times=1)])
    a, eng = make_engine(rng, fault_plan=plan)
    out = eng(rng.uniform(0, 10, (64,)).astype(np.float32))
    assert np.isnan(out[0])


def test_per_request_integrity_override(devices, rng):
    plan = FaultPlan([FaultSpec(site="dispatch", kind="nan")])
    a, eng = make_engine(rng, fault_plan=plan)  # engine default: gate off
    from matvec_mpi_multiplier_tpu.resilience import ResultIntegrityError

    x = rng.uniform(0, 10, (64,)).astype(np.float32)
    with pytest.raises(ResultIntegrityError):
        eng.submit(x, integrity=True).result()


# ------------------------------------------------------- close semantics


def test_close_is_idempotent_and_flushes_failed_traces(devices, rng, tmp_path):
    """ISSUE 7 small fix: close() must be idempotent and exception-safe —
    traces flush even when in-flight futures hold failures."""
    import json

    trace_path = tmp_path / "trace.jsonl"
    plan = FaultPlan(
        [FaultSpec(site="dispatch", kind="device_error", after=1)]
    )
    a, eng = make_engine(
        rng, fault_plan=plan, trace_jsonl=str(trace_path)
    )
    x = rng.uniform(0, 10, (64,)).astype(np.float32)
    ok = eng.submit(x)  # healthy (after=1 spares it)
    with pytest.raises(DeviceFaultError):
        eng.submit(x)  # the failure an in-flight stream would hold
    eng.close()
    eng.close()  # idempotent: second close is a no-op, not an error
    records = [
        json.loads(line)
        for line in trace_path.read_text().splitlines() if line
    ]
    assert any(r["status"] == "dispatch_failed" for r in records)
    # the healthy future still materializes after close (device work done)
    np.testing.assert_allclose(ok.result(), a @ x, rtol=1e-5)


def test_close_without_sink_is_safe(devices, rng):
    _, eng = make_engine(rng)
    eng.close()
    eng.close()


# --------------------------------------------------- chaos acceptance


@pytest.mark.chaos
def test_chaos_200_request_coalesced_trace_bisection_exactness(devices, rng):
    """ISSUE 7 acceptance: a 200-request coalesced serve trace with ≥5 %
    poisoned dispatch faults completes with every non-poisoned request
    returning a BITWISE-correct result — batch bisection isolates exactly
    the poisoned requests, and the bucket-preserving re-pad keeps
    survivors on the same executable with the same padded width as the
    unfaulted batch."""
    m = k = 64
    n_requests, batch = 200, 8
    a = rng.uniform(0, 10, (m, k)).astype(np.float32)
    mesh = make_mesh(8)
    poison = 1e30

    cols = [
        rng.uniform(0, 10, (k,)).astype(np.float32)
        for _ in range(n_requests)
    ]
    poison_rng = np.random.default_rng(11)
    poisoned = set(
        int(i) for i in poison_rng.choice(n_requests, size=11, replace=False)
    )
    assert len(poisoned) / n_requests >= 0.05
    for i in poisoned:
        cols[i][0] = np.float32(poison)

    def run(fault):
        plan = (
            FaultPlan([FaultSpec(
                site="dispatch", kind="device_error", poison=poison,
            )])
            if fault else None
        )
        eng = MatvecEngine(
            a, mesh, strategy="rowwise", max_bucket=batch, promote=1,
            fault_plan=plan,
        )
        # width == max_bucket triggers the inline flush: deterministic
        # batches of 8 in submission order, no flusher thread involved.
        sched = ArrivalWindowScheduler(
            eng, window_ms=1000.0, auto_flush=False, flush_width=batch,
        )
        futs = [sched.submit(c) for c in cols]
        sched.flush()
        outs = []
        for f in futs:
            try:
                outs.append(f.result(timeout=10))
            except DeviceFaultError:
                outs.append(None)
        sched.close()
        return outs, eng

    reference, _ = run(fault=False)
    assert all(r is not None for r in reference)
    chaotic, eng = run(fault=True)

    for i in range(n_requests):
        if i in poisoned:
            assert chaotic[i] is None, f"poisoned request {i} served"
        else:
            assert chaotic[i] is not None, f"healthy request {i} failed"
            np.testing.assert_array_equal(
                chaotic[i], reference[i],
                err_msg=f"request {i} not bitwise vs the unfaulted run",
            )

    counters = eng.metrics.snapshot()["counters"]
    assert counters["sched_isolated_failures_total"] == len(poisoned)
    assert counters["sched_bisect_splits_total"] >= len(poisoned)
    assert counters["engine_dispatch_failures_total"] >= len(poisoned)
    assert counters["resil_faults_injected_total"] >= len(poisoned)


@pytest.mark.chaos
def test_chaos_scheduler_integrity_gate_isolates_corrupt_column(devices, rng):
    """One corrupt column in a coalesced batch fails ONE caller; the
    batchmates' slices are finite and serve."""
    from matvec_mpi_multiplier_tpu.resilience import ResultIntegrityError

    plan = FaultPlan([FaultSpec(site="dispatch", kind="nan", times=1)])
    a, eng = make_engine(
        rng, promote=1, fault_plan=plan, integrity_gate=True
    )
    sched = ArrivalWindowScheduler(
        eng, window_ms=1000.0, auto_flush=False, flush_width=8
    )
    cols = [
        rng.uniform(0, 10, (64,)).astype(np.float32) for _ in range(8)
    ]
    futs = [sched.submit(c) for c in cols]
    sched.flush()
    outcomes = []
    for c, f in zip(cols, futs):
        try:
            np.testing.assert_allclose(f.result(timeout=10), a @ c, rtol=1e-5)
            outcomes.append("ok")
        except ResultIntegrityError:
            outcomes.append("refused")
    assert outcomes.count("refused") == 1
    assert eng.metrics.snapshot()["counters"][
        "engine_integrity_failures_total"
    ] == 1
    sched.close()

#!/usr/bin/env python
"""Headline benchmark: blockwise distributed matvec on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The flagship configuration is the blockwise strategy (the reference's best
performer, BASELINE.md) in amortized mode (operands HBM-resident; the honest
TPU number — the reference's in-loop redistribution measures PCIe on TPU, see
SURVEY.md §7 hard part (i)) at bf16, on whatever devices are available. The
baseline is the reference's best aggregate effective bandwidth anywhere in its
committed data: 4.13 GB/s (blockwise 10200² p=12, BASELINE.md), since the
reference is bandwidth-bound and GB/s is the dtype-fair comparison.

Timing uses the device-looped slope method by default (bench/timing.py,
measure='loop'): the rep loop is a lax.fori_loop inside one jitted
computation, so per-matvec time is the slope between two loop lengths with
ONE dispatch and one fence each — robust on tunneled PJRT backends where
block_until_ready returns early, a fetch costs a ~30-70 ms round-trip, and
each dispatch pays ~0.5 ms transport. MATVEC_BENCH_MEASURE=chain selects the
host-driven chain variant.

Environment overrides: MATVEC_BENCH_SIZE (default 32768), MATVEC_BENCH_REPS
(default 50), MATVEC_BENCH_DTYPE (default bfloat16), MATVEC_BENCH_KERNEL
(default pallas on TPU — the tiled VMEM-pipeline kernel sustains ~750-780
GB/s at 32768² bf16 on v5e, consistently above the XLA dot; "xla" elsewhere,
since off-TPU pallas runs in interpret mode).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

from matvec_mpi_multiplier_tpu import get_strategy, make_mesh
from matvec_mpi_multiplier_tpu.bench.timing import time_fn_chained, time_fn_looped

# Reference best: blockwise 10200^2 p=12, 0.201654 s -> 4.13 GB/s aggregate
# (data/out/blockwise.csv:37; derivation in BASELINE.md).
REFERENCE_BEST_GBPS = 4.13


def _backend_reachable(timeout_s: float = 90.0, attempts: int = 2) -> str | None:
    """Probe jax.devices() in a subprocess; return an error string or None.

    The tunneled TPU backend has been observed wedging so hard that
    jax.devices() blocks forever in C++ (uninterruptible by signals). Probing
    in a killable subprocess keeps bench.py from hanging the whole driver.

    Cost discipline: a wedge is permanent for the life of the tunnel, so a
    probe *timeout* reports immediately — retrying would burn minutes of
    driver wall-clock re-measuring a known state. Only a probe that *crashes*
    (nonzero exit: transient plugin/import error) earns a short-delay retry;
    its stderr tail is carried into the failure line so a crash isn't
    misreported as a timeout.
    """
    import subprocess
    import time

    last_error = "unknown"
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s, capture_output=True, text=True,
            )
            if r.returncode == 0:
                return None
            tail = (r.stderr or "").strip().splitlines()
            last_error = f"probe exited {r.returncode}: " + (
                tail[-1] if tail else "no stderr"
            )
        except subprocess.TimeoutExpired:
            return (
                f"probe timed out after {timeout_s:.0f}s "
                "(wedged tunnel — permanent, not retried)"
            )
        if i + 1 < attempts:
            time.sleep(15)
    return f"{last_error} ({attempts} attempts)"


def _cpu_fallback(dtype: str, probe_error: str) -> int:
    """Accelerator unreachable: measure the same blockwise path on the CPU
    backend at a CPU-sane size and report it with explicit provenance."""
    size = int(os.environ.get("MATVEC_BENCH_CPU_SIZE", 8192))
    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform

    # Config-level platform pin (env alone is outranked) AND host-device
    # count pinned to 1: an inherited --xla_force_host_platform_device_count
    # would otherwise build a multi-device mesh whose collectives can stall
    # 8-way-oversubscribed on a 1-core host.
    configure_platform("cpu", 1)

    import jax
    import jax.numpy as jnp

    # CPU has no native bf16: measure fp32 (honestly labeled) instead of a
    # bf16 emulation number that reflects neither backend. fp64 needs the
    # x64 flag or operands silently downcast while the label still says
    # float64 (timing.py::_prepare_operands applies the same guard).
    if dtype == "bfloat16":
        dtype = "float32"
    if dtype == "float64":
        jax.config.update("jax_enable_x64", True)
    mesh = make_mesh()  # single CPU device: no collectives to stall on
    strategy = get_strategy("blockwise")
    strategy.validate(size, size, mesh)
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.uniform(0, 10, (size, size)).astype(dtype))
    x = jnp.asarray(rng.uniform(0, 10, size).astype(dtype))
    fn = strategy.build(mesh)
    times = time_fn_chained(fn, (a, x), n_reps=10, warmup=2)
    t = float(np.median(times))
    gbps = jnp.dtype(dtype).itemsize * (size * size + 2 * size) / t / 1e9
    payload = {
        "metric": f"blockwise_{size}x{size}_{dtype}_matvec_bandwidth_cpu_fallback",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / REFERENCE_BEST_GBPS, 2),
        "backend": "cpu-fallback",
        "error": f"accelerator backend unreachable: {probe_error}",
    }
    # The fallback must stay an honest CPU measurement of THIS run — but a
    # wedged round end should not erase the round's real TPU evidence from
    # the headline record, so point at the committed north-star artifact
    # (written only by a successful on-chip baseline stage, never by a
    # fallback) with explicit provenance.
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BASELINE_65536_bf16.json")
    try:
        with open(artifact) as f:
            committed = json.load(f)
        payload["committed_tpu_evidence"] = {
            **{k: committed[k] for k in ("metric", "value", "unit",
                                         "vs_baseline") if k in committed},
            "source": "BASELINE_65536_bf16.json — measured on the TPU in "
            "an earlier healthy tunnel window, NOT by this run",
        }
    except (OSError, ValueError):
        pass
    print(json.dumps(payload))
    return 0


def main() -> int:
    size = int(os.environ.get("MATVEC_BENCH_SIZE", 32768))
    n_reps = int(os.environ.get("MATVEC_BENCH_REPS", 50))
    dtype = os.environ.get("MATVEC_BENCH_DTYPE", "bfloat16")
    measure = os.environ.get("MATVEC_BENCH_MEASURE", "loop")
    if measure not in ("loop", "chain"):
        # Validate before the 90s probe / mesh build / 8.6 GB operand gen.
        print(
            f"MATVEC_BENCH_MEASURE must be 'loop' or 'chain', got {measure!r}",
            file=sys.stderr,
        )
        return 2

    probe_error = _backend_reachable()
    if probe_error is not None:
        # Degrade to an honest, clearly-labeled CPU measurement rather than
        # recording 0.0: a wedged tunnel says nothing about the framework,
        # and the CPU number is a real end-to-end run of the same strategy
        # path. The metric name and a backend field mark the substitution so
        # it can never be mistaken for an accelerator result.
        return _cpu_fallback(dtype, probe_error)
    from matvec_mpi_multiplier_tpu.ops.pallas_gemv import _on_tpu

    # Default to the Pallas kernel only on real TPU hardware: off-TPU it runs
    # in interpret mode, which at this size would effectively hang.
    kernel = os.environ.get(
        "MATVEC_BENCH_KERNEL", "pallas" if _on_tpu() else "xla"
    )

    import jax
    import jax.numpy as jnp

    if dtype == "float64":
        # Without x64, astype('float64') under jit silently produces fp32
        # while itemsize below still counts 8 bytes — a ~2x inflated,
        # mislabeled number (same guard as timing._prepare_operands).
        jax.config.update("jax_enable_x64", True)

    mesh = make_mesh()
    strategy = get_strategy("blockwise")
    strategy.validate(size, size, mesh)
    sh_a, sh_x = strategy.shardings(mesh)

    # Operands filled on device with the strategy sharding — multi-GB arrays
    # never cross the host link. An iota-derived fill (values cycling in
    # [0, 10), matching the reference generator's range, README.md:32) keeps
    # the fill kernel trivial to compile; a bandwidth benchmark is
    # value-independent.
    @jax.jit
    def gen():
        # 2-D broadcasted iotas, NOT a flat iota of size*size elements: at
        # the 65536^2 north-star config a 1-D int32 iota has 4.3e9 > 2^31
        # elements (index overflow) and would be a 17 GB intermediate if
        # XLA ever materialized it; the broadcasted form keeps every value
        # <= 2*size and fuses into the bf16 output write.
        ir = jax.lax.broadcasted_iota(jnp.int32, (size, size), 0)
        ic = jax.lax.broadcasted_iota(jnp.int32, (size, size), 1)
        a = ((ir + ic) % 1024).astype(dtype) * (10.0 / 1024.0)
        ix = jax.lax.iota(jnp.int32, size)
        x = (ix % 1024).astype(dtype) * (10.0 / 1024.0)
        return (
            jax.lax.with_sharding_constraint(a, sh_a),
            jax.lax.with_sharding_constraint(x, sh_x),
        )

    a, x = gen()
    fn = strategy.build(mesh, kernel=kernel)
    # Median of DEFAULT_CHAIN_SAMPLES independent slope samples after a
    # multi-run warm-up: a cold process under-reports on its first chains,
    # and the median rejects the stray slow sample the mean would absorb.
    # Default 'loop' runs the rep loop on device (one dispatch per sample —
    # per-dispatch tunnel transport never touches the number); 'chain' is
    # the host-driven variant, adequate at this size where per-op time
    # (~3 ms) dwarfs dispatch cost.
    if measure == "loop":
        times = time_fn_looped(fn, (a, x), n_reps=n_reps, warmup=3)
    else:
        times = time_fn_chained(fn, (a, x), n_reps=n_reps, warmup=8)
    mean_t = float(np.median(times))
    itemsize = jnp.dtype(dtype).itemsize
    gbps = itemsize * (size * size + 2 * size) / mean_t / 1e9
    print(
        json.dumps(
            {
                "metric": f"blockwise_{size}x{size}_{dtype}_matvec_bandwidth",
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / REFERENCE_BEST_GBPS, 2),
                "measure": measure,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

// Native text-file loader for the data convention.
//
// Reference analog: load_matr / load_vec (src/matr_utils.c:42-83) — the
// reference's IO layer is native C reading whitespace-separated %lf tokens.
// This loader slurps the file and walks it with an exact int64-mantissa
// parser (strtod_l fallback for e-notation / long tokens), measuring ~3x
// faster than numpy's C tokenizer at the reference's sweep sizes, bitwise
// identical — it keeps the reference-faithful --use-files benchmark path
// cheap at full size (10200^2 doubles as %.4f text is ~800 MB).
//
// Contract (see utils/io.py):
//   returns n <= capacity   — number of doubles parsed (EOF reached);
//   returns capacity + 1    — the file holds MORE than `capacity` values
//                             (the extras are not written);
//   returns -1              — file could not be opened/read;
//   returns -3              — malformed content (non-numeric tokens / fused
//                             tokens); caller falls back to numpy so both
//                             paths reject the same files.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <locale.h>
#include <vector>

namespace {

// Exact powers of ten representable as doubles (10^0 .. 10^22).
constexpr double kPow10[] = {1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,
                             1e8,  1e9,  1e10, 1e11, 1e12, 1e13, 1e14, 1e15,
                             1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

inline bool IsSpace(char c) {
  return c == ' ' || c == '\n' || c == '\t' || c == '\r' || c == '\f' ||
         c == '\v';
}

// Fast correctly-rounded parser for the common fixed-notation case
// (<= 15 significant digits, small exponent): the mantissa accumulates
// exactly in int64 and the single scale by an *exact* power of ten (multiply
// for >=0, divide for <0 — both one IEEE rounding) matches strtod bit for
// bit. Anything outside that envelope (huge digit counts, e-notation with
// large exponents, inf/nan) falls back to strtod.
inline double ParseDouble(const char* p, const char** end) {
  const char* orig = p;  // returned via *end when nothing parses
  while (IsSpace(*p)) ++p;
  const char* start = p;
  bool neg = false;
  if (*p == '+' || *p == '-') neg = (*p++ == '-');

  uint64_t mant = 0;
  int digits = 0, frac = 0;
  for (; *p >= '0' && *p <= '9'; ++p) {
    mant = mant * 10 + static_cast<uint64_t>(*p - '0');
    ++digits;
  }
  if (*p == '.') {
    ++p;
    for (; *p >= '0' && *p <= '9'; ++p) {
      mant = mant * 10 + static_cast<uint64_t>(*p - '0');
      ++digits;
      ++frac;
    }
  }
  if (*p == 'x' || *p == 'X') {
    // C99 hex-float ('0x1p3'): strtod accepts it but numpy rejects it — the
    // two paths must reject identical files, so fail the token here (the
    // caller's trailing-content check then reports the file malformed).
    *end = orig;
    return 0.0;
  }
  if (digits == 0 || digits > 15 || *p == 'e' || *p == 'E' || *p == 'n' ||
      *p == 'N' || *p == 'i' || *p == 'I') {
    // strtod_l with a cached C locale: plain strtod honors LC_NUMERIC, so an
    // embedding app under e.g. de_DE (comma decimal separator) would silently
    // misparse '1.5e3' — the numpy path is locale-independent and this one
    // must match it.
    static locale_t c_locale = newlocale(LC_ALL_MASK, "C", nullptr);
    char* e2 = nullptr;
    double v = strtod_l(start, &e2, c_locale);
    *end = (e2 == start) ? orig : e2;
    return v;
  }
  double v = static_cast<double>(mant);  // exact: mant < 10^15 < 2^53
  if (frac > 0) v /= kPow10[frac];       // exact divisor: one rounding
  *end = p;
  return neg ? -v : v;
}

}  // namespace

extern "C" int64_t matvec_load_text(const char* path, double* out,
                                    int64_t capacity) {
  FILE* f = std::fopen(path, "rb");
  if (f == nullptr) return -1;
  if (std::fseek(f, 0, SEEK_END) != 0) {
    std::fclose(f);
    return -1;
  }
  long size = std::ftell(f);
  if (size < 0) {
    std::fclose(f);
    return -1;
  }
  std::rewind(f);
  // +1 for a NUL terminator so strtod never walks off the buffer.
  std::vector<char> buf(static_cast<size_t>(size) + 1);
  size_t got = std::fread(buf.data(), 1, static_cast<size_t>(size), f);
  std::fclose(f);
  buf[got] = '\0';

  const char* p = buf.data();
  int64_t n = 0;
  // Line-structure tracking: np.loadtxt skips blank lines but rejects ragged
  // ones ("Wrong number of columns at line N"), even when the total element
  // count matches — both parser paths must reject identical files, so the
  // first non-blank line fixes the expected token count and every later
  // non-blank line must match it.
  int64_t tokens_in_line = 0;
  int64_t tokens_per_line = -1;
  auto end_line = [&]() -> bool {  // false => ragged line structure
    if (tokens_in_line == 0) return true;  // blank line: skipped, like numpy
    if (tokens_per_line < 0) {
      tokens_per_line = tokens_in_line;
    } else if (tokens_in_line != tokens_per_line) {
      return false;
    }
    tokens_in_line = 0;
    return true;
  };
  while (n < capacity) {
    while (IsSpace(*p)) {
      if (*p == '\n' && !end_line()) return -3;
      ++p;
    }
    const char* end = nullptr;
    double v = ParseDouble(p, &end);
    if (end == p) break;  // no more parseable tokens
    // Tokens must be whitespace-separated: a fused token like '1.5-2.5'
    // (which numpy rejects) must not silently split into two values.
    if (!IsSpace(*end) && *end != '\0') return -3;
    ++tokens_in_line;
    out[n++] = v;
    p = end;
  }
  // Whatever remains must be pure whitespace (EOF) or, at capacity, more
  // well-formed values (count mismatch). Anything else is malformed.
  while (IsSpace(*p)) {
    if (*p == '\n' && !end_line()) return -3;
    ++p;
  }
  if (*p == '\0') {
    if (!end_line()) return -3;  // final line, no trailing newline
    return n;
  }
  if (n == capacity) {
    const char* end = nullptr;
    (void)ParseDouble(p, &end);
    if (end != p && (IsSpace(*end) || *end == '\0')) return capacity + 1;
  }
  return -3;
}

// Native C++ GEMV kernel: the framework's host-side native compute tier.
//
// Reference analog: multiply_std_rowwise (src/matr_utils.c:86-96), the serial
// dense row-major dot-product kernel the reference compiles with mpicc. Here
// the same kernel is exposed two ways:
//   * plain extern "C" entry points (matvec_gemv_f32/f64) for ctypes use as a
//     host-side oracle;
//   * typed XLA FFI handlers (GemvF32/GemvF64) registered as CPU custom
//     calls, so the native kernel participates in jitted/shard_mapped JAX
//     programs off-TPU (the true native-code execution path).
//
// Build: `make` in this directory (links against nothing; XLA FFI headers
// ship with jaxlib, see Makefile).

#include <cstdint>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

template <typename T>
void GemvKernel(const T* a, const T* x, T* y, int64_t m, int64_t k) {
  for (int64_t i = 0; i < m; ++i) {
    const T* row = a + i * k;
    // Four partial accumulators break the sequential-add dependence chain so
    // the compiler can keep the FMA pipes full after vectorizing.
    T acc0 = 0, acc1 = 0, acc2 = 0, acc3 = 0;
    int64_t j = 0;
    for (; j + 4 <= k; j += 4) {
      acc0 += row[j] * x[j];
      acc1 += row[j + 1] * x[j + 1];
      acc2 += row[j + 2] * x[j + 2];
      acc3 += row[j + 3] * x[j + 3];
    }
    for (; j < k; ++j) acc0 += row[j] * x[j];
    y[i] = (acc0 + acc1) + (acc2 + acc3);
  }
}

}  // namespace

extern "C" {

void matvec_gemv_f32(const float* a, const float* x, float* y, int64_t m,
                     int64_t k) {
  GemvKernel(a, x, y, m, k);
}

void matvec_gemv_f64(const double* a, const double* x, double* y, int64_t m,
                     int64_t k) {
  GemvKernel(a, x, y, m, k);
}

}  // extern "C"

template <ffi::DataType DT>
static ffi::Error GemvImpl(ffi::Buffer<DT> a, ffi::Buffer<DT> x,
                           ffi::ResultBuffer<DT> y) {
  auto dims = a.dimensions();
  if (dims.size() != 2) {
    return ffi::Error::InvalidArgument("gemv: a must be rank 2");
  }
  int64_t m = dims[0];
  int64_t k = dims[1];
  if (x.element_count() != k) {
    return ffi::Error::InvalidArgument("gemv: x length must equal a cols");
  }
  if (y->element_count() != m) {
    return ffi::Error::InvalidArgument("gemv: y length must equal a rows");
  }
  GemvKernel(a.typed_data(), x.typed_data(), y->typed_data(), m, k);
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(GemvF32, GemvImpl<ffi::F32>,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(GemvF64, GemvImpl<ffi::F64>,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F64>>()
                                  .Arg<ffi::Buffer<ffi::F64>>()
                                  .Ret<ffi::Buffer<ffi::F64>>());

// Native C++ GEMM kernel: the host-side native tier's rank-2 face.
//
// Reference analog: the reference's compute layer is matvec-only
// (multiply_std_rowwise, src/matr_utils.c:86-96); GEMM is this framework's
// extension of the same native-kernel pattern (see gemv.cc) to C = A @ B.
// Exposed the same two ways:
//   * extern "C" entry points (matvec_gemm_f32/f64) for ctypes oracle use;
//   * typed XLA FFI handlers (GemmF32/GemmF64) registered as CPU custom
//     calls, so the native kernel runs inside jitted/shard_mapped programs.
//
// Kernel shape: i-l-j loops with a k-strip block. The innermost j loop
// streams one row of B against a scalar of A — contiguous loads/stores the
// compiler vectorizes — while the l-strip keeps the active rows of B hot in
// L1/L2 across the i sweep.

#include <cstdint>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

template <typename T>
void GemmKernel(const T* a, const T* b, T* c, int64_t m, int64_t k,
                int64_t n) {
  constexpr int64_t kStrip = 64;
  for (int64_t i = 0; i < m; ++i) {
    T* crow = c + i * n;
    for (int64_t j = 0; j < n; ++j) crow[j] = 0;
  }
  for (int64_t l0 = 0; l0 < k; l0 += kStrip) {
    const int64_t l1 = (l0 + kStrip < k) ? l0 + kStrip : k;
    for (int64_t i = 0; i < m; ++i) {
      const T* arow = a + i * k;
      T* crow = c + i * n;
      for (int64_t l = l0; l < l1; ++l) {
        const T av = arow[l];
        const T* brow = b + l * n;
        for (int64_t j = 0; j < n; ++j) crow[j] += av * brow[j];
      }
    }
  }
}

}  // namespace

extern "C" {

void matvec_gemm_f32(const float* a, const float* b, float* c, int64_t m,
                     int64_t k, int64_t n) {
  GemmKernel(a, b, c, m, k, n);
}

void matvec_gemm_f64(const double* a, const double* b, double* c, int64_t m,
                     int64_t k, int64_t n) {
  GemmKernel(a, b, c, m, k, n);
}

}  // extern "C"

template <ffi::DataType DT>
static ffi::Error GemmImpl(ffi::Buffer<DT> a, ffi::Buffer<DT> b,
                           ffi::ResultBuffer<DT> c) {
  auto adims = a.dimensions();
  auto bdims = b.dimensions();
  if (adims.size() != 2 || bdims.size() != 2) {
    return ffi::Error::InvalidArgument("gemm: a and b must be rank 2");
  }
  const int64_t m = adims[0];
  const int64_t k = adims[1];
  const int64_t n = bdims[1];
  if (bdims[0] != k) {
    return ffi::Error::InvalidArgument("gemm: b rows must equal a cols");
  }
  if (c->element_count() != m * n) {
    return ffi::Error::InvalidArgument("gemm: c must hold m*n elements");
  }
  GemmKernel(a.typed_data(), b.typed_data(), c->typed_data(), m, k, n);
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(GemmF32, GemmImpl<ffi::F32>,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(GemmF64, GemmImpl<ffi::F64>,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F64>>()
                                  .Arg<ffi::Buffer<ffi::F64>>()
                                  .Ret<ffi::Buffer<ffi::F64>>());

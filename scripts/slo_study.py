#!/usr/bin/env python
"""SLO / flight-recorder evidence: the resilience-demo chaos trace
replayed with the observability control plane armed (ISSUE 19;
docs/OBSERVABILITY.md).

One serve-load run on the ``data/resilience_demo/`` chaos protocol
(256x256 fp32 colwise ``psum_scatter``, burst arrivals coalesced through
the arrival-window scheduler, four seeded fault families at once) with
the three new planes recording:

* the **correlated event timeline** streams to ``events.jsonl`` — every
  decision/consequence line carrying ``request_id`` or ``cause_id``;
* the **flight recorder** auto-dumps a post-mortem bundle into
  ``flight/`` on the first typed failures;
* the **SLO burn-rate monitor** is then driven on a fake clock: six
  hours of clean traffic at the run's measured rate, then the run's own
  measured failure fraction as a sustained incident — the multi-window
  page alert MUST fire (asserted before anything is committed), and the
  evaluation is written to ``slo.json``.

The fake-clock replay is the point, not a workaround: burn-rate alerts
are promises over hours of history, and the monitor's injectable clock
is how hours of history are captured (and CI-gated) in seconds — the
same mechanism the unit tests pin the alert algebra with.

Committed artifacts under ``--out`` (``data/slo_demo/``), gated by
``tests/test_data_quality.py``:

* ``events.jsonl`` — the full correlated timeline of the chaos run;
* ``flight/flight_*.json`` — the auto-dumped post-mortem bundle(s);
* ``slo.json`` — the burn-rate evaluation with the fired page alert;
* ``metrics.json`` — the run's registry snapshot (slo_* gauges included);
* ``summary.json`` — the headline: the failed request whose causal story
  ``obs timeline`` reconstructs, the fired alerts, the chaos tallies;
* ``README.md`` — the rendered timeline + how to re-capture.

Usage::

    python scripts/slo_study.py --platform cpu --host-devices 8 \
        --out data/slo_demo
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# The resilience-demo chaos protocol (data/resilience_demo/README.md),
# verbatim: targeted dispatch faults on the exotic psum_scatter config,
# 5% poisoned payloads, NaN corruption behind the integrity gate, and a
# background transient-fault rate.
SHAPE = 256
N_REQUESTS = 200
MAX_BUCKET = 32
RATE_REQ_S = 100.0
BURST = 8
FAULT_SPEC = (
    "dispatch:device_error:key=*psum_scatter*,times=12;"
    "dispatch:nan:times=2,after=40;"
    "dispatch:device_error:p=0.04,retryable=1"
)
FAULT_SEED = 7
POISON_RATE = 0.05
BREAKER_RESET_S = 0.6
SEED = 0

# The replay protocol: 6 h of clean history at the run's measured
# request rate, then the run's measured failure fraction sustained for a
# 30-minute incident. The page policy needs burn > 14.4 on BOTH the 5 m
# and the 1 h window: against the 99.9% objective that is a failure
# fraction above 1.44% *averaged over the hour*, so the ~5% chaos
# fraction must run for at least ~17 min — 30 min gives 1 h burn ~2x the
# threshold with the 5 m window far past it.
GOOD_HISTORY_S = 6 * 3600.0
INCIDENT_S = 1800.0
REPLAY_STEP_S = 60.0


def replay_slo(run_snapshot: dict, *, failed: int, offered: int) -> dict:
    """Drive a fake-clock SloMonitor through good history + the run's
    measured incident; return (evaluation, monitor-registry snapshot)."""
    from matvec_mpi_multiplier_tpu.obs import (
        DEFAULT_TARGETS,
        MetricsRegistry,
        SloMonitor,
    )

    fail_frac = failed / offered
    chaos_p99 = (
        run_snapshot.get("histograms", {})
        .get("serve_e2e_latency_ms", {})
        .get("p99")
    )
    reg = MetricsRegistry()
    total = reg.counter("serve_requests_total")
    bad = reg.counter("serve_failed_requests_total")
    g_p99 = reg.gauge("serve_e2e_latency_ms")
    clock = {"t": 0.0}
    mon = SloMonitor(reg, DEFAULT_TARGETS, clock=lambda: clock["t"])
    # Healthy-traffic latency for the clean history; the incident brings
    # the chaos run's measured p99 (which also breaches the 50 ms bound
    # when the chaos trace was slow enough to).
    p99_bound = next(
        t.objective for t in DEFAULT_TARGETS if t.name == "e2e_p99_ms"
    )
    clean_p99 = p99_bound * 0.6
    incident_p99 = chaos_p99 if chaos_p99 is not None else clean_p99

    def tick(frac: float, p99: float) -> None:
        clock["t"] += REPLAY_STEP_S
        n = max(1, int(RATE_REQ_S * REPLAY_STEP_S))
        total.inc(n)
        bad.inc(int(round(n * frac)))
        g_p99.set(p99)
        mon.sample()

    while clock["t"] < GOOD_HISTORY_S:
        tick(0.0, clean_p99)
    assert not mon.evaluate()["alerts"], "alert fired on clean history"
    while clock["t"] < GOOD_HISTORY_S + INCIDENT_S:
        tick(fail_frac, incident_p99)
    return mon.evaluate()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--out", default="data/slo_demo")
    parser.add_argument("--platform", default="cpu")
    parser.add_argument("--host-devices", type=int, default=8)
    args = parser.parse_args(argv)

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)

    from matvec_mpi_multiplier_tpu.bench.serve import run_serve_load
    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform
    from matvec_mpi_multiplier_tpu.obs import FAILURE_KINDS
    from matvec_mpi_multiplier_tpu.obs.__main__ import (
        render_slo,
        render_timeline,
    )
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh

    configure_platform(args.platform, args.host_devices)
    mesh = make_mesh(args.host_devices)

    print("== chaos run with timeline + flight recorder armed ==")
    result = run_serve_load(
        "colwise", mesh, SHAPE, SHAPE,
        combine="psum_scatter",
        n_requests=N_REQUESTS, max_bucket=MAX_BUCKET,
        arrival="burst", rate=RATE_REQ_S, burst=BURST, coalesce=True,
        fault_spec=FAULT_SPEC, fault_seed=FAULT_SEED,
        poison_rate=POISON_RATE, integrity_gate=True,
        breaker_reset_s=BREAKER_RESET_S, seed=SEED,
        events_jsonl=str(out / "events.jsonl"),
        flight_dir=str(out / "flight"),
        metrics_out=str(out / "metrics.json"),
    )
    failed = result.failed_requests
    offered = result.n_requests
    print(
        f"chaos run: {failed} of {offered} failed "
        f"({result.retries} retries, {result.downgrades} downgrades)"
    )
    assert failed > 0, (
        "the chaos trace failed nothing — no incident to demonstrate"
    )

    events = [
        json.loads(line)
        for line in (out / "events.jsonl").read_text().splitlines()
    ]
    assert events and all(
        "request_id" in e or "cause_id" in e for e in events
    ), "an event line is missing its correlation id"
    failures = [
        e for e in events
        if e["kind"] in FAILURE_KINDS
        and ("request_id" in e or "cause_id" in e)
    ]
    assert failures, "chaos produced no typed-failure timeline events"
    failed_ev = failures[0]
    failed_rid = failed_ev.get("request_id", failed_ev.get("cause_id"))

    dumps = sorted((out / "flight").glob("flight_*.json"))
    assert dumps, "the flight recorder dumped nothing under chaos"
    print(f"flight dumps: {[d.name for d in dumps]}")

    print("== fake-clock SLO replay (6 h clean + the incident) ==")
    run_snapshot = json.loads((out / "metrics.json").read_text())
    evaluation = replay_slo(run_snapshot, failed=failed, offered=offered)
    pages = [
        a for a in evaluation["alerts"] if a["severity"] == "page"
    ]
    assert pages, (
        f"no page alert fired: {json.dumps(evaluation['alerts'])}"
    )
    (out / "slo.json").write_text(json.dumps(evaluation, indent=2) + "\n")
    print(render_slo(evaluation))

    timeline_text = render_timeline(events, failed_rid)
    summary = {
        "failed_request_id": failed_rid,
        "failed_request_kind": failed_ev["kind"],
        "failed_requests": failed,
        "offered_requests": offered,
        "retries": result.retries,
        "downgrades": result.downgrades,
        "alerts": evaluation["alerts"],
        "flight_dumps": [d.name for d in dumps],
        "n_events": len(events),
    }
    (out / "summary.json").write_text(json.dumps(summary, indent=2) + "\n")

    readme = f"""# SLO burn-rate + flight-recorder demo (CPU mesh, seeded chaos)

The committed proof of the observability control plane (`obs/timeline.py`,
`obs/slo.py`, `obs/flight.py`; docs/OBSERVABILITY.md): the
`data/resilience_demo/` chaos trace re-captured with the correlated
event timeline streaming, the flight recorder armed, and the SLO
burn-rate monitor replaying the run's measured failure fraction over a
fake-clock history — one page alert fires, one post-mortem bundle is
dumped, and one failed request's causal story is reconstructable from
the committed events.

Capture command (repo root):

```
JAX_PLATFORMS=cpu python scripts/slo_study.py \\
    --platform cpu --host-devices 8 --out data/slo_demo
```

The run: {offered} burst-arrival requests, {failed} failed under the
four seeded fault families ({result.retries} retries,
{result.downgrades} ladder downgrades absorbed the rest). The replay:
six hours of clean traffic at {RATE_REQ_S:.0f} req/s, then the measured
{failed / offered:.1%} failure fraction for {INCIDENT_S / 60:.0f} minutes — burn
{pages[0]["burn_short"]:.0f}x over 5m and {pages[0]["burn_long"]:.0f}x
over 1h against the 99.9% availability objective, past the 14.4x page
threshold on both windows.

Artifacts:

* `events.jsonl` — the correlated timeline ({len(events)} events; every
  line carries `request_id` or `cause_id`);
* `flight/{dumps[0].name}` — the auto-dumped bundle (trigger
  `{json.loads(dumps[0].read_text())["trigger"]["kind"]}`);
* `slo.json` — the evaluation with the fired page alert
  (`python -m matvec_mpi_multiplier_tpu.obs slo data/slo_demo/slo.json`);
* `metrics.json` — the run's registry snapshot;
* `summary.json` — the headline numbers the data-quality gate asserts.

One failed request's causal story
(`python -m matvec_mpi_multiplier_tpu.obs timeline
data/slo_demo/events.jsonl {failed_rid}`):

```
{timeline_text}
```
"""
    (out / "README.md").write_text(readme)
    print(f"committed: {sorted(p.name for p in out.iterdir())}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Data-file generator CLI.

The reference generates its input matrices externally with numpy and saves
them as %.4f text (README.md:32) but never commits the generator; its
``.gitignore`` excludes the resulting ``*.txt``. This script IS that missing
generator, emitting files in the exact ``data/matrix_<r>_<c>.txt`` /
``data/vector_<n>.txt`` convention (src/matr_utils.c:9-18).

Examples::

    python scripts/generate_data.py 600 600            # one square pair
    python scripts/generate_data.py --sweep square     # the full test.sh:8 set
    python scripts/generate_data.py --sweep asymmetric # 120..1200 x 60000
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from matvec_mpi_multiplier_tpu.bench.sweep import ASYMMETRIC_SIZES, SQUARE_SIZES
from matvec_mpi_multiplier_tpu.utils import io


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("n_rows", nargs="?", type=int)
    p.add_argument("n_cols", nargs="?", type=int)
    p.add_argument("--sweep", choices=["square", "asymmetric"], default=None)
    p.add_argument("--data-root", default=None)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    if args.sweep == "square":
        sizes = [(s, s) for s in SQUARE_SIZES]
    elif args.sweep == "asymmetric":
        sizes = list(ASYMMETRIC_SIZES)
    elif args.n_rows and args.n_cols:
        sizes = [(args.n_rows, args.n_cols)]
    else:
        p.error("give n_rows n_cols, or --sweep square|asymmetric")

    for n_rows, n_cols in sizes:
        mp = io.save_matrix(
            io.generate_matrix(n_rows, n_cols, seed=args.seed), args.data_root
        )
        vp = io.save_vector(
            io.generate_vector(n_cols, seed=args.seed + 1), args.data_root
        )
        print(f"{mp}  {vp}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Long-context attention study: ring vs Ulysses vs replicated, measured.

``parallel/attention.py`` ships both canonical sequence-parallel
schedules; this study measures them against each other and against the
no-parallelism baseline (fully replicated dense attention) over a
sequence-length ladder on whatever backend is active, writing
``docs/ATTENTION.md`` — the same committed-evidence discipline as
OVERLAP/COMPENSATED/REFINEMENT. Timing uses the hardened device-looped
slope protocol (``bench/timing.py::time_fn_looped``), so tunnel dispatch
jitter never touches the numbers.

Correctness is asserted in-line before timing (ring and Ulysses vs the
replicated dense result at every config): a speed table for operators
that silently diverged would be worse than no table.

Usage::

    python scripts/attention_study.py --platform cpu --host-devices 8
    python scripts/attention_study.py --seqs 4096 16384   # real backend
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--platform", default=None)
    p.add_argument("--host-devices", type=int, default=None)
    p.add_argument("--seqs", nargs="+", type=int, default=[1024, 4096])
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--d-head", type=int, default=64)
    p.add_argument("--dtype", default="bfloat16",
                   help="storage dtype (statistics are always fp32)")
    p.add_argument("--causal", action="store_true")
    p.add_argument("--n-reps", type=int, default=10)
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--report", default=str(REPO / "docs" / "ATTENTION.md"))
    p.add_argument("--no-report", action="store_true")
    args = p.parse_args(argv)

    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform

    configure_platform(args.platform, args.host_devices)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from matvec_mpi_multiplier_tpu.bench.timing import time_fn_looped
    from matvec_mpi_multiplier_tpu.parallel.attention import (
        build_ring_attention,
        build_ulysses_attention,
    )
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.utils.errors import TimingError

    platform = jax.devices()[0].platform
    n_dev = args.devices or len(jax.devices())
    mesh = make_mesh(n_dev)
    h, dh = args.heads, args.d_head
    dtype = jnp.dtype(args.dtype)
    rng = np.random.default_rng(args.seed)

    # The replicated baseline: dense multi-head attention, no sequence
    # sharding — what a single device (or naive replication) would run.
    @jax.jit
    def dense(q, kv):
        k, v = kv[0], kv[1]
        d = q.shape[-1]
        scores = jnp.einsum(
            "qhd,khd->hqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) * (1.0 / (d ** 0.5))
        if args.causal:
            n = q.shape[0]
            rows = jax.lax.iota(jnp.int32, n)
            scores = jnp.where(
                (rows[None, :] <= rows[:, None])[None], scores, -jnp.inf
            )
        m = jnp.max(scores, axis=-1, keepdims=True)
        w = jnp.exp(scores - m)
        o = jnp.einsum("hqk,khd->qhd", w, v.astype(jnp.float32))
        return o / jnp.swapaxes(jnp.sum(w, axis=-1), 0, 1)[..., None]

    ring = build_ring_attention(mesh, causal=args.causal)
    uly = build_ulysses_attention(mesh, causal=args.causal)

    rows = []
    for s in args.seqs:
        q, k, v = (
            jnp.asarray(rng.standard_normal((s, h, dh)), dtype)
            for _ in range(3)
        )
        kv = jnp.stack([k, v])
        # Correctness first: both schedules vs the replicated dense result.
        oracle = np.asarray(dense(q, kv))
        tol = 2e-2 if dtype == jnp.bfloat16 else 2e-5
        for name, fn in (("ring", ring), ("ulysses", uly)):
            got = np.asarray(
                jax.jit(lambda q_, kv_: fn(q_, kv_[0], kv_[1]))(q, kv)
            )
            np.testing.assert_allclose(got, oracle, rtol=tol, atol=tol)
        entry = {"s": s}
        flops = 4.0 * s * s * h * dh * (0.5 if args.causal else 1.0)
        timed = {
            "dense_replicated": lambda q_, kv_: dense(q_, kv_),
            "ring": lambda q_, kv_: ring(q_, kv_[0], kv_[1]),
            "ulysses": lambda q_, kv_: uly(q_, kv_[0], kv_[1]),
        }
        for name, fn in timed.items():
            try:
                times = time_fn_looped(fn, (q, kv), n_reps=args.n_reps)
                t = float(np.median(times))
                entry[name] = {"ms": t * 1e3, "gflops": flops / t / 1e9}
                print(f"s={s} {name:16s}: {t * 1e3:8.3f} ms "
                      f"({entry[name]['gflops']:.1f} GFLOP/s)")
            except TimingError as e:
                entry[name] = None
                print(f"s={s} {name}: UNMEASURABLE ({e})", file=sys.stderr)
        rows.append(entry)

    report = [
        "# Long-context attention schedules: measured evidence",
        "",
        f"Backend: **{platform}**, {n_dev}-device mesh; multi-head "
        f"attention h={h}, d_head={dh}, {args.dtype} storage / fp32 "
        f"statistics, causal={args.causal}; device-looped slope timing "
        f"({args.n_reps} reps; generated by `scripts/attention_study.py`). "
        "Both schedules are asserted equal to the replicated dense result "
        "at every config before timing.",
        "",
        "| seq len | dense (replicated) ms | ring ms | ulysses ms |",
        "|---|---|---|---|",
    ]
    for r in rows:
        cells = [
            f"{r[k]['ms']:.3f}" if r.get(k) else "unmeasurable"
            for k in ("dense_replicated", "ring", "ulysses")
        ]
        report.append(f"| {r['s']} | " + " | ".join(cells) + " |")
    report += [
        "",
        "`ring` (`parallel/attention.py::ring_attention`) circulates KV "
        "blocks over p−1 single-neighbor ppermute hops with a "
        "flash-attention online softmax — O(s/p·d) per-device memory, the "
        "s×s score matrix never exists. `ulysses` reshards to a "
        "head-parallel layout with ONE balanced all_to_all each way and "
        "runs dense per-head attention — one low-latency exchange against "
        "O(s²/p) per-device scores. The dense column is the "
        "no-sequence-parallelism baseline: every device holds (or one "
        "device computes) the full problem. On the virtual CPU mesh these "
        "numbers only sanity-check the plumbing; the TPU capture "
        "(`scripts/tpu_measure_all.py`, attention stage) lands the ICI "
        "numbers this table exists for.",
    ]
    text = "\n".join(report) + "\n"
    print("\n" + text)
    if not args.no_report:
        out = Path(args.report)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(text)
        print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

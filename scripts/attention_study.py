#!/usr/bin/env python
"""Long-context attention study: ring vs Ulysses vs replicated, measured.

``parallel/attention.py`` ships both canonical sequence-parallel
schedules; this study measures them against each other and against the
no-parallelism baseline (fully replicated dense attention) over a
sequence-length ladder on whatever backend is active, writing
``docs/ATTENTION.md`` — the same committed-evidence discipline as
OVERLAP/COMPENSATED/REFINEMENT. Timing uses the hardened device-looped
slope protocol (``bench/timing.py::time_fn_looped``), so tunnel dispatch
jitter never touches the numbers.

Correctness is asserted in-line before timing (ring and Ulysses vs the
replicated dense result at every config): a speed table for operators
that silently diverged would be worse than no table.

Usage::

    python scripts/attention_study.py --platform cpu --host-devices 8
    python scripts/attention_study.py --seqs 4096 16384   # real backend
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--platform", default=None)
    p.add_argument("--host-devices", type=int, default=None)
    p.add_argument("--seqs", nargs="+", type=int, default=[1024, 4096])
    p.add_argument("--heads", type=int, default=8)
    # 128 = the TPU lane width: the Pallas flash tier tiles (rather than
    # falling back) exactly when d_head is a lane multiple, and 128 is the
    # transformer-typical head size anyway.
    p.add_argument("--d-head", type=int, default=128)
    p.add_argument("--dtype", default="bfloat16",
                   help="storage dtype (statistics are always fp32)")
    p.add_argument("--causal", action="store_true")
    p.add_argument("--n-reps", type=int, default=10)
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--report", default=str(REPO / "docs" / "ATTENTION.md"))
    p.add_argument("--no-report", action="store_true")
    args = p.parse_args(argv)

    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform

    configure_platform(args.platform, args.host_devices)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from matvec_mpi_multiplier_tpu.bench.timing import time_fn_looped
    from matvec_mpi_multiplier_tpu.parallel.attention import (
        build_ring_attention,
        build_ulysses_attention,
    )
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.utils.errors import TimingError

    platform = jax.devices()[0].platform
    n_dev = args.devices or len(jax.devices())
    mesh = make_mesh(n_dev)
    h, dh = args.heads, args.d_head
    dtype = jnp.dtype(args.dtype)
    rng = np.random.default_rng(args.seed)

    # The replicated baseline: dense multi-head attention, no sequence
    # sharding — what a single device (or naive replication) would run.
    @jax.jit
    def dense(q, kv):
        k, v = kv[0], kv[1]
        d = q.shape[-1]
        scores = jnp.einsum(
            "qhd,khd->hqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) * (1.0 / (d ** 0.5))
        if args.causal:
            n = q.shape[0]
            rows = jax.lax.iota(jnp.int32, n)
            scores = jnp.where(
                (rows[None, :] <= rows[:, None])[None], scores, -jnp.inf
            )
        m = jnp.max(scores, axis=-1, keepdims=True)
        w = jnp.exp(scores - m)
        o = jnp.einsum("hqk,khd->qhd", w, v.astype(jnp.float32))
        return o / jnp.swapaxes(jnp.sum(w, axis=-1), 0, 1)[..., None]

    from matvec_mpi_multiplier_tpu.ops.pallas_attention import (
        flash_path_available,
    )

    schedules = {
        "ring": build_ring_attention(mesh, causal=args.causal),
        "ring_flash": build_ring_attention(
            mesh, causal=args.causal, kernel="flash"
        ),
        "ulysses": build_ulysses_attention(mesh, causal=args.causal),
        "ulysses_flash": build_ulysses_attention(
            mesh, causal=args.causal, kernel="flash"
        ),
    }

    def flash_fallbacks(s: int) -> set[str]:
        """Which *_flash variants run the plain-JAX fallback at this s:
        the ring's per-hop blocks are (s/p, s/p); Ulysses' local step sees
        the full sequence. Same predicate the tier itself branches on —
        a fallback timing must never be labeled as the Pallas kernel."""
        blk = s // n_dev
        out = set()
        if not flash_path_available(blk, blk, dh):
            out.add("ring_flash")
        if not flash_path_available(s, s, dh):
            out.add("ulysses_flash")
        return out

    rows = []
    for s in args.seqs:
        q, k, v = (
            jnp.asarray(rng.standard_normal((s, h, dh)), dtype)
            for _ in range(3)
        )
        kv = jnp.stack([k, v])
        entry = {"s": s, "fallbacks": flash_fallbacks(s)}
        flops = 4.0 * s * s * h * dh * (0.5 if args.causal else 1.0)
        # Correctness first: every schedule × tier vs the replicated dense
        # result. Per-VARIANT isolation: a tier that fails to compile or
        # diverges on this backend (e.g. a Mosaic lowering quirk in the
        # fused tile on real hardware) must cost only its own column, not
        # the whole stage — the capture gets one shot per healthy window
        # and the xla-tier numbers are evidence regardless.
        oracle = np.asarray(dense(q, kv))
        tol = 2e-2 if dtype == jnp.bfloat16 else 2e-5
        broken = set()
        for name, fn in schedules.items():
            try:
                got = np.asarray(
                    jax.jit(lambda q_, kv_: fn(q_, kv_[0], kv_[1]))(q, kv)
                )
                np.testing.assert_allclose(got, oracle, rtol=tol, atol=tol)
            except Exception as e:  # compile failure or oracle mismatch
                broken.add(name)
                entry[name] = None
                print(f"s={s} {name}: VARIANT FAILED "
                      f"({type(e).__name__}: {str(e)[:200]})",
                      file=sys.stderr)
        timed = {"dense_replicated": lambda q_, kv_: dense(q_, kv_)}
        for name, fn in schedules.items():
            if name not in broken:
                timed[name] = (
                    lambda q_, kv_, fn=fn: fn(q_, kv_[0], kv_[1])
                )
        for name, fn in timed.items():
            try:
                times = time_fn_looped(fn, (q, kv), n_reps=args.n_reps)
                t = float(np.median(times))
                entry[name] = {"ms": t * 1e3, "gflops": flops / t / 1e9}
                print(f"s={s} {name:16s}: {t * 1e3:8.3f} ms "
                      f"({entry[name]['gflops']:.1f} GFLOP/s)")
            except TimingError as e:
                entry[name] = None
                print(f"s={s} {name}: UNMEASURABLE ({e})", file=sys.stderr)
        entry["broken"] = sorted(broken)
        rows.append(entry)

    cols = (
        "dense_replicated", "ring", "ring_flash", "ulysses", "ulysses_flash"
    )
    report = [
        "# Long-context attention schedules: measured evidence",
        "",
        f"Backend: **{platform}**, {n_dev}-device mesh; multi-head "
        f"attention h={h}, d_head={dh}, {args.dtype} storage / fp32 "
        f"statistics, causal={args.causal}; device-looped slope timing "
        f"({args.n_reps} reps; generated by `scripts/attention_study.py`). "
        "Every timed cell passed an oracle-equality assertion against the "
        "replicated dense result before timing. Cells marked `†` hit the "
        "flash tier's plain-JAX fallback (block shape does not admit the "
        "128-lane tiling) — they time the fallback, NOT the Pallas "
        "kernel. A `FAILED` cell means that variant did not compile or "
        "did not match the oracle on this backend (the failure is in the "
        "study's stderr and the stage exits nonzero); `unmeasurable` "
        "means it ran correctly but the backend was too noisy to time it.",
        "",
        "| seq len | dense (replicated) ms | ring ms | ring_flash ms "
        "| ulysses ms | ulysses_flash ms |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        cells = [
            (f"{r[k]['ms']:.3f}" + ("†" if k in r["fallbacks"] else ""))
            if r.get(k)
            else ("FAILED" if k in r.get("broken", ()) else "unmeasurable")
            for k in cols
        ]
        report.append(f"| {r['s']} | " + " | ".join(cells) + " |")
    report += [
        "",
        "`ring` (`parallel/attention.py::ring_attention`) circulates KV "
        "blocks over p−1 single-neighbor ppermute hops with a "
        "flash-attention online softmax — O(s/p·d) per-device memory, the "
        "s×s score matrix never exists. KV rides the wire at its storage "
        "dtype (bf16 = half the ICI bytes of fp32; the per-tile upcast is "
        "exact), as does the forward Ulysses reshard — Ulysses' return "
        "leg carries the fp32 output at full width per the accumulator "
        "contract. `ulysses` reshards to a "
        "head-parallel layout with ONE balanced all_to_all each way and "
        "runs dense per-head attention — one low-latency exchange against "
        "O(s²/p) per-device scores. The dense column is the "
        "no-sequence-parallelism baseline: every device holds (or one "
        "device computes) the full problem. The `*_flash` columns run the "
        "same schedules with the fused Pallas tile "
        "(`ops/pallas_attention.py`): scores, online softmax, and the "
        "weighted-V product in one VMEM pipeline, the score tile never "
        "reaching HBM. Off-TPU the Pallas tile executes in interpret mode, "
        "so non-TPU `*_flash` timings are correctness evidence only — the "
        "fusion's cost/benefit is a TPU question.",
        "",
        "## Scope of the evidence this environment can produce",
        "",
        "This environment has **one TPU chip**. A sequence-parallel "
        "schedule's win is an ICI win — p devices each holding s/p of the "
        "sequence — and with p=1 there is no ICI, so **the multi-chip "
        "performance story is out of scope here by construction**, not "
        "pending. Concretely:",
        "",
        "- Virtual-CPU-mesh rows in this table are a **plumbing sanity "
        "check**: they demonstrate that all schedule × tier combinations "
        "are oracle-equal and that the collective choreography (p−1 "
        "ppermute hops; one all_to_all each way) executes with the "
        "expected asymptotic shape. CPU collective times say nothing "
        "about ICI; ring trailing dense at small s is expected there "
        "(many tiny dispatches against one fused one).",
        "- On the single TPU chip both schedules **deliberately collapse "
        "to p=1 dense attention**, so TPU rows will not show a "
        "ring-vs-dense win and no number here should be read as one. "
        "What the TPU rows DO measure is (a) that the schedules compile "
        "and run on the TPU backend, (b) the single-chip MXU attention "
        "throughput a p-device run would scale from, and (c) the one "
        "genuine single-chip comparison: the fused Pallas tile vs the "
        "score-materializing XLA tier at the same schedule.",
        "- The multi-chip correctness story (the part that needs no real "
        "ICI) is covered by oracle equality on the 8-device CPU mesh "
        "(`tests/test_attention.py`) and compile+execute in the 8-device "
        "multichip dryrun (`__graft_entry__.py::dryrun_multichip`).",
    ]
    text = "\n".join(report) + "\n"
    print("\n" + text)
    if not args.no_report:
        out = Path(args.report)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(text)
        print(f"wrote {out}")
    broken_any = sorted({b for r in rows for b in r.get("broken", ())})
    if broken_any:
        # Report written (healthy variants' evidence is safe); the stage
        # still fails so the capture's per-stage record shows the finding.
        print(f"variant failure(s): {', '.join(broken_any)}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Online-resharding evidence: the drifting-shape A/B capture (ISSUE 18
acceptance; docs/RESHARDING.md).

One seeded protocol (:func:`~matvec_mpi_multiplier_tpu.bench.serve.
run_reshard_drift`), run twice: a 3-tenant Zipf fleet registered in the
calibrated cost model's predicted-WORST layout for the steady traffic
shape serves a trace that drifts at the rollover index — width-1
vectors trickling below the amortization threshold before it, closed-
loop 32-column blocks after it. ``--reshard off`` keeps the fleet
frozen in the registered layout; ``--reshard auto`` lets the
``GlobalScheduler`` crossover trigger migrate each tenant's resident
``A`` on-device (``MatrixRegistry.reshard`` — pure collectives, the
``hlo-reshard-schedule``-audited programs) once its EWMA demand
amortizes the migration. Each arm runs in its OWN subprocess so
allocator state from one arm cannot bias the other's percentiles.

Committed artifacts under ``--out`` (``data/reshard_demo/``), gated by
``tests/test_data_quality.py``:

* ``tuning_cache.json`` — the full (6-probe) calibration both the
  registration-layout pick and the trigger's predictions came from.
* ``out/reshard_ab.csv`` — both arms' rows: pre/steady p50/p99,
  migration counts and bytes, per-phase compile counts, the request
  index of the last migration, final per-tenant strategies.
* ``decisions.jsonl`` — the auto arm's full decision trace; the
  ``reshard`` decisions carry ``predicted_s`` (the migration cost) and
  the crossover-plus-amortization reason.
* ``metrics.json`` — the auto arm's registry snapshot
  (``registry_reshards_total`` / ``reshard_bytes_total`` /
  ``gsched_reshards_total`` — the counters the obs panel renders).
* ``summary.json`` — the A/B headline, asserted before anything is
  written: auto must beat off on steady-state p99 AND p50, every
  migration must land before the steady window opens, steady-phase
  compiles must be ZERO in both arms (the one-time new-layout compile
  rides the migration's ``warm_widths``, never a steady request), and
  every reshard decision must carry ``predicted_s`` + reason.

Usage::

    python scripts/reshard_study.py --platform cpu --host-devices 8 \
        --out data/reshard_demo
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# The committed protocol. Shape chosen where the measured layout gap is
# wide on the CPU mesh AND points the same way as the calibrated
# model's ranking (tall-narrow A, wide steady blocks: the predicted-
# worst blockwise pays two collective launches per request where the
# predicted-best rowwise pays one cheap output gather). The pre-phase
# trickle (6 req/s fleet-wide over 3 tenants, EWMA horizon 0.5 s)
# keeps every tenant's amortization horizon under one request, so the
# trigger provably waits for the demand+shape drift.
M, K = 8192, 256
WIDTH_STEADY = 32
N_TENANTS = 3
ZIPF_A = 1.1
N_REQUESTS = 280
ROLLOVER = 24
STEADY_SKIP = 56
PRE_RATE = 6.0
SEED = 0
CALIB_REPS = 10


def run_arm(args) -> int:
    """Child mode: one A/B arm in a fresh process. Reads the shared
    tuning cache (env), writes the result dict as JSON to --result."""
    from matvec_mpi_multiplier_tpu.bench.serve import run_reshard_drift
    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh

    configure_platform(args.platform, args.host_devices)
    mesh = make_mesh(args.host_devices)
    result = run_reshard_drift(
        args.src, mesh, M, K,
        n_tenants=N_TENANTS, zipf_a=ZIPF_A, n_requests=N_REQUESTS,
        rollover=ROLLOVER, width_steady=WIDTH_STEADY, pre_rate=PRE_RATE,
        steady_skip=STEADY_SKIP, seed=SEED, reshard=args.arm,
        metrics_out=args.metrics_out or None,
        decision_jsonl=args.decision_jsonl or None,
    )
    Path(args.result).write_text(json.dumps(result, indent=2) + "\n")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--out", default="data/reshard_demo")
    parser.add_argument("--platform", default="cpu")
    parser.add_argument("--host-devices", type=int, default=8)
    # Child-mode plumbing (internal; the parent spawns itself):
    parser.add_argument("--arm", choices=["off", "auto"], default=None)
    parser.add_argument("--src", default=None)
    parser.add_argument("--result", default=None)
    parser.add_argument("--metrics-out", default=None)
    parser.add_argument("--decision-jsonl", default=None)
    args = parser.parse_args(argv)

    if args.arm is not None:
        return run_arm(args)

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    # The demo's tuning cache IS an artifact: the calibration that
    # picked the registration layout and armed the trigger travels
    # with the numbers it explains. The env var is inherited by the
    # arm subprocesses, so all three consult the SAME record.
    os.environ["MATVEC_TUNING_CACHE"] = str(out / "tuning_cache.json")

    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform
    from matvec_mpi_multiplier_tpu.models import get_strategy
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.parallel.reshard import (
        RESHARD_STRATEGIES,
    )
    from matvec_mpi_multiplier_tpu.tuning.cache import (
        TuningCache,
        calibration_key,
    )
    from matvec_mpi_multiplier_tpu.tuning.cost_model import (
        CostModel,
        calibrate,
    )

    configure_platform(args.platform, args.host_devices)
    mesh = make_mesh(args.host_devices)
    p = int(mesh.devices.size)

    print("== full calibration (6 probes) ==")
    cal = calibrate(mesh, level="full", n_reps=CALIB_REPS)
    cache = TuningCache.load()
    cache.record(calibration_key(p), cal.to_record())
    cache.save()

    # The fleet registers in the model's predicted-WORST layout for the
    # steady shape — the drifted trace strands it on the wrong side of
    # the crossover surface, which is exactly the situation online
    # resharding exists for.
    model = CostModel(cal)
    predicted = {}
    for s in RESHARD_STRATEGIES:
        combine = get_strategy(s).default_combine(mesh)
        predicted[s] = model.predict(
            s, combine, m=M, k=K, p=p, dtype="float32", b=WIDTH_STEADY
        ).total_s
    src = max(predicted, key=predicted.get)
    print(
        "predicted steady ms/req: "
        + "  ".join(f"{s}={t * 1e3:.3f}" for s, t in predicted.items())
        + f"  -> registering in {src}"
    )

    def spawn(arm: str, extra: list[str]) -> dict:
        result_path = out / f".{arm}_result.json"
        cmd = [
            sys.executable, __file__, "--arm", arm, "--src", src,
            "--platform", args.platform,
            "--host-devices", str(args.host_devices),
            "--result", str(result_path),
        ] + extra
        print(f"== --reshard {arm} (subprocess) ==")
        subprocess.run(cmd, check=True, cwd=REPO)
        result = json.loads(result_path.read_text())
        result_path.unlink()
        return result

    off = spawn("off", [])
    auto = spawn("auto", [
        "--metrics-out", str(out / "metrics.json"),
        "--decision-jsonl", str(out / "decisions.jsonl"),
    ])

    summary = {
        "protocol": {
            "m": M, "k": K, "p": p, "src": src,
            "predicted_steady_s": predicted,
            "n_tenants": N_TENANTS, "zipf_a": ZIPF_A,
            "n_requests": N_REQUESTS, "rollover": ROLLOVER,
            "steady_skip": STEADY_SKIP, "width_steady": WIDTH_STEADY,
            "pre_rate_req_s": PRE_RATE, "seed": SEED,
            "calibration_level": cal.level,
        },
        "off": off,
        "auto": auto,
    }
    print(json.dumps(summary, indent=2))

    # ---- the acceptance gates, asserted BEFORE committing anything ----
    window = ROLLOVER + STEADY_SKIP
    failures = []
    if not auto["p99_steady_ms"] < off["p99_steady_ms"]:
        failures.append(
            "steady p99 not better: "
            f"{auto['p99_steady_ms']:.2f} vs {off['p99_steady_ms']:.2f}"
        )
    if not auto["p50_steady_ms"] < off["p50_steady_ms"]:
        failures.append(
            "steady p50 not better: "
            f"{auto['p50_steady_ms']:.2f} vs {off['p50_steady_ms']:.2f}"
        )
    if auto["reshards"] < 1:
        failures.append("auto arm never migrated")
    if off["reshards"] != 0:
        failures.append(f"off arm migrated {off['reshards']} times")
    if not (0 <= auto["last_reshard_at"] < window):
        failures.append(
            f"migration at request {auto['last_reshard_at']} did not "
            f"land before the steady window (opens at {window})"
        )
    for arm, r in (("off", off), ("auto", auto)):
        if r["compiles_steady"] != 0:
            failures.append(
                f"{arm} arm compiled {r['compiles_steady']} times in "
                "the steady window"
            )
    expected_bytes = auto["reshards"] * M * K * 4
    if auto["reshard_bytes"] != expected_bytes:
        failures.append(
            f"reshard_bytes {auto['reshard_bytes']} != "
            f"{auto['reshards']} migrations x {M * K * 4} payload bytes"
        )
    if set(off["final_strategies"].values()) != {src}:
        failures.append("off arm did not stay frozen in the src layout")
    if not any(s != src for s in auto["final_strategies"].values()):
        failures.append("auto arm's fleet still entirely in src layout")
    decisions = [
        json.loads(ln)
        for ln in (out / "decisions.jsonl").read_text().splitlines()
    ]
    reshard_decisions = [
        d for d in decisions if d.get("decision") == "reshard"
    ]
    if len(reshard_decisions) != auto["reshards"]:
        failures.append(
            f"{len(reshard_decisions)} reshard decisions traced but "
            f"{auto['reshards']} migrations counted"
        )
    for d in reshard_decisions:
        if not (d.get("predicted_s") and "amortizes" in d.get("reason", "")
                and d.get("src") == src and d.get("dst")):
            failures.append(f"undertraced reshard decision: {d}")
    metrics = json.loads((out / "metrics.json").read_text())
    counters = metrics["counters"]
    if counters.get("registry_reshards_total") != auto["reshards"]:
        failures.append("metrics.json reshard counter disagrees")
    if failures:
        print("GATE FAILURES:\n  " + "\n  ".join(failures),
              file=sys.stderr)
        return 1

    from matvec_mpi_multiplier_tpu.bench.serve import (
        append_reshard_result,
    )

    for result in (off, auto):
        append_reshard_result(result, root=out)
    (out / "summary.json").write_text(json.dumps(summary, indent=2) + "\n")
    print(f"\ncommitted A/B capture -> {out}")
    print(
        f"  steady p99 {off['p99_steady_ms']:.2f} -> "
        f"{auto['p99_steady_ms']:.2f} ms, p50 "
        f"{off['p50_steady_ms']:.2f} -> {auto['p50_steady_ms']:.2f} ms "
        f"({auto['reshards']} migrations, "
        f"{auto['reshard_bytes'] / 1e6:.1f} MB moved, last at request "
        f"{auto['last_reshard_at']}, steady compiles 0/0)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python
"""Quantized-storage evidence: the tuner's sixth-axis race + the error
budget, measured (docs/QUANTIZATION.md; ISSUE 8 acceptance).

Two artifacts, on whatever backend is active:

* **The storage race** — ``tuning.search.tune_storage`` for each
  requested (strategy, m, k) config: every supported format quantized,
  placed, and raced as the full distributed matvec, winners + per-
  candidate resident bytes and achieved bandwidth persisted to a v4
  cache in ``--out``. The race is honest by construction: on the CPU
  mesh XLA converts int8 scalar-wise and ``native`` wins (recorded
  exactly so — the same "measure, don't assume" outcome as the overlap
  demo's S=1); the quantized formats win where the upcast fuses into
  the MXU operand stream.
* **Error-budget compliance** — per format, the distributed matvec vs
  the numpy fp64 oracle: normwise residual against the budget seats
  (``ops.quantize.FP32_LEVEL_RELERR`` for int8c; the one-level bound
  for int8/fp8) plus the resident-bytes ratio, written to
  ``errors.json`` and gated by ``tests/test_data_quality.py``.

Usage::

    python scripts/quantized_study.py --platform cpu --host-devices 8 \
        --out data/quantized_demo
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# (strategy, m, k) cells raced by default: one output-sharded and one
# contraction-sharded strategy at a bandwidth-relevant size.
DEFAULT_CONFIGS = (("rowwise", 512, 4096), ("colwise", 512, 4096))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="data/quantized_demo",
                   help="output directory (cache + errors.json)")
    p.add_argument("--platform", default=None,
                   help="JAX_PLATFORMS override (e.g. cpu)")
    p.add_argument("--host-devices", type=int, default=None,
                   help="virtual CPU device count (XLA host platform)")
    p.add_argument("--strategy", nargs="+", default=None,
                   help="strategies to race (default: rowwise colwise)")
    p.add_argument("--sizes", nargs="+", type=int, default=None,
                   help="square sizes overriding the default config cells")
    p.add_argument("--n-reps", type=int, default=30,
                   help="timing reps per candidate")
    p.add_argument("--samples", type=int, default=3,
                   help="slope samples per candidate")
    p.add_argument("--seed", type=int, default=0)
    return p


def error_study(configs, seed: int) -> dict:
    """Normwise residual vs the fp64 oracle per (config, format), with
    the budget seat each format must clear."""
    import jax
    import numpy as np

    from matvec_mpi_multiplier_tpu import get_strategy, make_mesh
    from matvec_mpi_multiplier_tpu.ops.quantize import (
        FP32_LEVEL_RELERR,
        INT8_EPS,
        quantize_matrix,
    )
    from matvec_mpi_multiplier_tpu.tuning.search import (
        storage_format_candidates,
    )
    from matvec_mpi_multiplier_tpu.utils.io import (
        generate_matrix,
        generate_vector,
    )

    mesh = make_mesh(len(jax.devices()))
    # Budget seats (docs/QUANTIZATION.md): int8c must reach the fp32-level
    # seat; the single-level formats carry the one-level bound scaled by
    # the contraction's cancellation-free worst case — in practice they
    # land near INT8_EPS itself on random data; pin 4x slack.
    budgets = {
        "int8": 4 * INT8_EPS, "fp8": 4 * INT8_EPS,
        "int8c": FP32_LEVEL_RELERR,
    }
    out: dict = {"budgets": budgets, "configs": {}}
    for name, m, k in configs:
        strat = get_strategy(name)
        a = np.asarray(generate_matrix(m, k, seed=seed), np.float32)
        x = np.asarray(generate_vector(k, seed=seed + 1), np.float32)
        oracle = a.astype(np.float64) @ x.astype(np.float64)
        scale = np.abs(oracle).max()
        sh_a, sh_x = strat.shardings(mesh)
        x_dev = jax.device_put(x, sh_x)
        shards = strat.contraction_shards(mesh)
        entry: dict = {}
        for fmt in storage_format_candidates("float32"):
            if fmt == "native":
                fn = strat.build(mesh)
                operand, nbytes = jax.device_put(a, sh_a), a.nbytes
            else:
                qa = quantize_matrix(a, fmt, contraction_shards=shards)
                fn = strat.build(mesh, dtype_storage=fmt)
                operand, nbytes = jax.device_put(qa, sh_a), qa.nbytes
            y = np.asarray(fn(operand, x_dev)).astype(np.float64)
            relerr = float(np.abs(y - oracle).max() / scale)
            entry[fmt] = {
                "max_relerr_vs_fp64": relerr,
                "bytes_ratio": round(nbytes / a.nbytes, 6),
                "budget": budgets.get(fmt),
                "within_budget": (
                    True if fmt == "native" else relerr <= budgets[fmt]
                ),
            }
        out["configs"][f"{name}|{m}x{k}"] = entry
    return out


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    if args.host_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.host_devices}"
            ).strip()
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from matvec_mpi_multiplier_tpu import make_mesh
    from matvec_mpi_multiplier_tpu.tuning.cache import TuningCache
    from matvec_mpi_multiplier_tpu.tuning.search import tune_storage

    out_dir = REPO / args.out
    out_dir.mkdir(parents=True, exist_ok=True)
    strategies = args.strategy or sorted({c[0] for c in DEFAULT_CONFIGS})
    if args.sizes:
        configs = [(s, n, n) for s in strategies for n in args.sizes]
    else:
        configs = [c for c in DEFAULT_CONFIGS if c[0] in strategies]

    mesh = make_mesh(len(jax.devices()))
    # load(), not a fresh cache: repeated study runs (new sizes, new
    # strategies) accumulate into one demo cache instead of clobbering
    # the earlier races.
    cache = TuningCache.load(out_dir / "tuning_cache.json")
    print(f"storage race on {mesh.devices.size} devices "
          f"({jax.devices()[0].platform}):")
    for name, m, k in configs:
        decision = tune_storage(
            name, mesh, m, k, "float32", cache,
            n_reps=args.n_reps, samples=args.samples, seed=args.seed,
            force=True,
        )
        if decision is not None:
            print(f"  -> {name} {m}x{k}: {decision['storage']}")
    cache.save()
    print(f"cache: {cache.path}")

    errors = error_study(configs, args.seed)
    # Merge-preserve earlier runs' configs (same doctrine as the cache).
    errors_path = out_dir / "errors.json"
    if errors_path.exists():
        try:
            prior = json.loads(errors_path.read_text())
            merged = dict(prior.get("configs", {}))
            merged.update(errors["configs"])
            errors["configs"] = merged
        except (json.JSONDecodeError, AttributeError):
            pass  # swallow-ok: a hand-damaged errors.json is simply rewritten from this run's measurements
    bad = [
        (cfg, fmt)
        for cfg, entry in errors["configs"].items()
        for fmt, row in entry.items()
        if not row["within_budget"]
    ]
    errors_path.write_text(
        json.dumps(errors, indent=1, sort_keys=True) + "\n"
    )
    print(f"errors: {errors_path}")
    for cfg, entry in errors["configs"].items():
        for fmt, row in entry.items():
            mark = "ok" if row["within_budget"] else "OVER BUDGET"
            print(f"  {cfg} {fmt}: relerr {row['max_relerr_vs_fp64']:.2e} "
                  f"bytes {row['bytes_ratio']:.3f}x [{mark}]")
    if bad:
        print(f"ERROR-BUDGET FAILURES: {bad}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Render the README per-size results table from the committed TPU dataset.

The README's Results section cites a per-size table that lands with the
loop-protocol capture; this renders it mechanically from
``data/out/results_extended.csv`` so landing the capture is a paste, not
an exercise (and reruns stay consistent with the data). Markdown goes to
stdout: one row per size, one column per strategy, cell = time (ms) with
aggregate effective GB/s.

Usage::

    python scripts/results_table.py                       # committed data
    python scripts/results_table.py --data-root /tmp/x --measure sync
"""

from __future__ import annotations

import argparse
import sys
from collections import defaultdict
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-root", default="data")
    p.add_argument("--measure", default="loop",
                   help="protocol filter (loop = the trusted TPU protocol)")
    p.add_argument("--mode", default="amortized")
    p.add_argument("--dtype", default="float32")
    p.add_argument("--devices", type=int, default=1)
    p.add_argument("--shape", choices=["square", "asym", "all"],
                   default="square")
    args = p.parse_args(argv)

    from matvec_mpi_multiplier_tpu.bench.metrics import read_csv

    ext = Path(args.data_root) / "out" / "results_extended.csv"
    if not ext.exists():
        print(f"no dataset at {ext}", file=sys.stderr)
        return 1
    rows = [
        r for r in read_csv(ext)
        if r["measure"] == args.measure and r["mode"] == args.mode
        and r["dtype"] == args.dtype and r["n_devices"] == args.devices
        and r["n_rhs"] == 1
    ]
    if args.shape != "all":
        want_square = args.shape == "square"
        rows = [r for r in rows if (r["n_rows"] == r["n_cols"]) == want_square]
    if not rows:
        print(
            f"no {args.measure}/{args.mode}/{args.dtype} p={args.devices} "
            f"rows in {ext}", file=sys.stderr,
        )
        return 1

    # cell[(size)][strategy] = (time, gbps); keep the last row per key
    # (append-only CSV: later rows supersede).
    cells: dict[tuple, dict] = defaultdict(dict)
    strategies: list[str] = []
    for r in rows:
        if r["strategy"] not in strategies:
            strategies.append(r["strategy"])
        cells[(r["n_rows"], r["n_cols"])][r["strategy"]] = (
            r["time"], r["gbps"]
        )
    strategies.sort()

    header = "| size | " + " | ".join(strategies) + " |"
    sep = "|---" * (len(strategies) + 1) + "|"
    lines = [header, sep]
    for (m, n) in sorted(cells, key=lambda s: (s[0] * s[1], s)):
        label = f"{m}²" if m == n else f"{m}×{n}"
        row = [label]
        for s in strategies:
            if s in cells[(m, n)]:
                t, g = cells[(m, n)][s]
                row.append(f"{t * 1e3:.3f} ms ({g:.0f} GB/s)")
            else:
                row.append("—")
        lines.append("| " + " | ".join(row) + " |")
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Global-scheduler evidence: the same-trace A/B capture (ISSUE 11
acceptance; docs/SCHEDULING.md).

One protocol, run twice on the SAME seeded 240-request Zipf chaos trace
(the ``data/multitenant_demo/`` fleet: 6 tenants' 128x128 fp32 matrices,
budget for 3, hottest pinned — plus an SLO overlay: 10 ms deadlines at
1000 req/s offered with seeded latency-fault stragglers and a
backpressure high-water mark): once greedy (``--global-sched off``),
once through the cost-model-driven global scheduler (``on``). Committed
artifacts under ``--out`` (``data/gsched_demo/``), gated by
``tests/test_data_quality.py``:

* ``tuning_cache.json`` — the quick calibration the scheduled run's
  predictions came from (cache schema v5).
* ``out/serve_tenants_rowwise.csv`` — BOTH runs' per-tenant rows (one
  ``ALL`` row per run, ``global_sched`` 0/1): the deadline_expires /
  rejected split, on-time goodput, end-to-end p50/p99, availability.
* ``decisions.jsonl`` — the scheduled run's full decision trace: every
  admit/reject/interleave/evict/flush with ``predicted_s`` and
  ``reason``.
* ``metrics.json`` — the scheduled run's registry snapshot (the
  ``gsched_*`` vocabulary the obs panel renders).
* ``summary.json`` — the A/B headline, asserted before anything is
  written: scheduling ON must show better p99 AND availability, ZERO
  engine deadline-expires (all converted to pre-dispatch rejects),
  at least the baseline's on-time goodput, and every decision carrying
  ``predicted_s``.

Usage::

    python scripts/gsched_study.py --platform cpu --host-devices 8 \
        --out data/gsched_demo
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# The committed protocol: the multitenant_demo fleet + the SLO overlay.
# Deadline/rate chosen so the offered load is ~2x what the straggler-
# afflicted fleet sustains inside the deadline — the regime where greedy
# queues-then-expires and admission control has something to decide.
N_TENANTS = 6
SHAPE = 128
ZIPF_A = 1.1
HBM_BUDGET = "3x"
PIN_HOT = 1
N_REQUESTS = 240
SEED = 0
DEADLINE_MS = 10.0
RATE_REQ_S = 1000.0
MAX_IN_FLIGHT = 4
DEADLINE_MARGIN = 1.5
DEMAND_WEIGHT = 2.0
FAULT_SPEC = "dispatch:latency:latency_ms=6,p=0.08"
FAULT_SEED = 7


def _row(result):
    all_row = result.rows[-1]
    served = (
        all_row.requests - all_row.failed_requests - all_row.rejected
    )
    return {
        "global_sched": result.global_sched,
        "deadline_expires": result.deadline_expires,
        "rejected": all_row.rejected,
        "failed": all_row.failed_requests,
        "served": served,
        "on_time": result.on_time,
        "p50_e2e_ms": result.p50_e2e_ms,
        "p99_e2e_ms": result.p99_e2e_ms,
        "availability": all_row.availability,
        "hit_rate": result.hit_rate,
        "evictions": all_row.evictions,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--out", default="data/gsched_demo")
    parser.add_argument("--platform", default="cpu")
    parser.add_argument("--host-devices", type=int, default=8)
    parser.add_argument("--calib-reps", type=int, default=5)
    args = parser.parse_args(argv)

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    # The demo's tuning cache IS an artifact: the calibration the
    # scheduled run consulted travels with the numbers it explains.
    os.environ["MATVEC_TUNING_CACHE"] = str(out / "tuning_cache.json")

    from matvec_mpi_multiplier_tpu.bench.serve import (
        append_multitenant_result,
        run_serve_multitenant,
    )
    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.tuning import reset_cache
    from matvec_mpi_multiplier_tpu.tuning.cache import (
        TuningCache,
        calibration_key,
    )
    from matvec_mpi_multiplier_tpu.tuning.cost_model import calibrate

    configure_platform(args.platform, args.host_devices)
    mesh = make_mesh(args.host_devices)

    print("== quick calibration (2 probes) ==")
    cal = calibrate(mesh, level="quick", n_reps=args.calib_reps)
    cache = TuningCache.load()
    cache.record(calibration_key(int(mesh.devices.size)), cal.to_record())
    cache.save()
    reset_cache()

    common = dict(
        n_tenants=N_TENANTS, zipf_a=ZIPF_A, hbm_budget=HBM_BUDGET,
        pin_hot=PIN_HOT, n_requests=N_REQUESTS, seed=SEED,
        max_in_flight=MAX_IN_FLIGHT, deadline_ms=DEADLINE_MS,
        rate=RATE_REQ_S, fault_spec=FAULT_SPEC, fault_seed=FAULT_SEED,
    )
    print("== greedy baseline (--global-sched off) ==")
    off = run_serve_multitenant(
        "rowwise", mesh, SHAPE, SHAPE, **common
    )
    print("== scheduled run (--global-sched on) ==")
    on = run_serve_multitenant(
        "rowwise", mesh, SHAPE, SHAPE, global_sched=True,
        demand_weight=DEMAND_WEIGHT, deadline_margin=DEADLINE_MARGIN,
        decision_jsonl=str(out / "decisions.jsonl"),
        metrics_out=str(out / "metrics.json"),
        **common,
    )

    summary = {
        "protocol": {
            "n_tenants": N_TENANTS, "shape": SHAPE, "zipf_a": ZIPF_A,
            "hbm_budget": HBM_BUDGET, "pin_hot": PIN_HOT,
            "n_requests": N_REQUESTS, "seed": SEED,
            "deadline_ms": DEADLINE_MS, "rate_req_s": RATE_REQ_S,
            "max_in_flight": MAX_IN_FLIGHT,
            "deadline_margin": DEADLINE_MARGIN,
            "demand_weight": DEMAND_WEIGHT,
            "fault_spec": FAULT_SPEC, "fault_seed": FAULT_SEED,
            "calibration_level": cal.level,
        },
        "greedy": _row(off),
        "scheduled": _row(on),
    }
    g, s = summary["greedy"], summary["scheduled"]
    print(json.dumps(summary, indent=2))

    # ---- the acceptance gates, asserted BEFORE committing anything ----
    failures = []
    if not s["p99_e2e_ms"] < g["p99_e2e_ms"]:
        failures.append(
            f"p99 not better: {s['p99_e2e_ms']:.2f} vs {g['p99_e2e_ms']:.2f}"
        )
    if not s["availability"] > g["availability"]:
        failures.append(
            f"availability not better: {s['availability']:.3f} vs "
            f"{g['availability']:.3f}"
        )
    if s["deadline_expires"] != 0:
        failures.append(
            f"scheduled run still expired {s['deadline_expires']} "
            "requests in an engine gate"
        )
    if s["rejected"] == 0:
        failures.append("scheduled run rejected nothing (no admission)")
    if g["deadline_expires"] == 0:
        failures.append("baseline never expired (overload too mild)")
    if not s["on_time"] >= g["on_time"]:
        failures.append(
            f"on-time goodput regressed: {s['on_time']} vs {g['on_time']}"
        )
    decisions = [
        json.loads(ln)
        for ln in (out / "decisions.jsonl").read_text().splitlines()
    ]
    if not decisions:
        failures.append("decision trace is empty")
    missing = [d for d in decisions if "predicted_s" not in d
               or "reason" not in d]
    if missing:
        failures.append(
            f"{len(missing)} decisions missing predicted_s/reason"
        )
    rejects = [d for d in decisions if d["decision"] == "reject"]
    unpredicted = [d for d in rejects if d["predicted_s"] is None]
    if unpredicted:
        failures.append(
            f"{len(unpredicted)} rejects carried predicted_s=None "
            "(rejecting without a prediction is the bug the cold-cache "
            "test pins)"
        )
    if failures:
        print("GATE FAILURES:\n  " + "\n  ".join(failures),
              file=sys.stderr)
        return 1

    for result in (off, on):
        append_multitenant_result(result, root=out)
    (out / "summary.json").write_text(json.dumps(summary, indent=2) + "\n")
    print(f"\ncommitted A/B capture -> {out}")
    print(
        f"  p99 {g['p99_e2e_ms']:.2f} -> {s['p99_e2e_ms']:.2f} ms, "
        f"availability {g['availability']:.3f} -> "
        f"{s['availability']:.3f}, on-time {g['on_time']} -> "
        f"{s['on_time']}, expires {g['deadline_expires']} -> 0 "
        f"(rejected fast: {s['rejected']})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

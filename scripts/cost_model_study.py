#!/usr/bin/env python
"""Cost-model evidence: calibration, the predicted crossover surface,
and the pruned-vs-exhaustive tuning parity capture (ISSUE 10
acceptance; docs/COST_MODEL.md).

Four committed artifacts under ``--out`` (``data/cost_model_demo/``),
gated by ``tests/test_data_quality.py``:

* ``calibration.json`` — the full 6-probe calibration measured on this
  backend (machine constants + the raw probe times they came from).
* ``crossover.csv`` — the predicted combine-crossover surface over
  (m, k, p, dtype) from that calibration: hardware-independent in p,
  so a TPU visit only has to validate the constants.
* ``prune_parity.csv`` — the acceptance capture: every tune_* axis run
  twice with REAL measurement (exhaustive vs ``prune_margin``), one row
  per axis×strategy with both decisions, the per-run measured-candidate
  counts, and the pruned candidates. The script fails loudly if any
  decision differs or the total measurement saving is under 40 %.
* ``metrics.json`` — the pruned run's obs registry snapshot: the
  predicted-vs-measured ratio histogram, the divergence gauge (the
  demo's documented bound lives in docs/COST_MODEL.md), the pruned
  counter matching the CSV, and one deliberate force re-measure so the
  ``tuning_cache_stale_total`` satellite is visible.

The two tuning caches (``exhaustive_cache.json``, ``pruned_cache.json``)
ride along as evidence — the pruned cache's decisions carry their
``predicted_s`` maps and ``pruned`` lists.

Usage::

    python scripts/cost_model_study.py --platform cpu --host-devices 8 \
        --out data/cost_model_demo
"""

from __future__ import annotations

import argparse
import csv
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# The tuned operand the parity capture races (global shape; storage uses
# a wider k so the resident stream is a real object), and the demo's
# hysteresis margin: 0.4 instead of the production 0.05, because the
# capture is run on whatever noisy CI host regenerates it — a 1-core
# host timing 8 rendezvousing device threads swings sync reps by tens
# of percent, and a noise-flipped near-tie would read as a parity
# failure when it is neither a model nor a tuner defect. Same reason
# the default rep count is 12: the ranking statistic is the MIN rep,
# and min-of-12 is stable where min-of-5 still flips.
PARITY_M = 64
PARITY_K = 64
PARITY_STORAGE_K = 1024
PARITY_MIN_GAIN = 0.4
STRATEGIES = ("rowwise", "colwise", "blockwise")


def _measured_counts(snapshot: dict) -> tuple[int, int]:
    """(measured, pruned) candidate totals from a registry snapshot."""
    from matvec_mpi_multiplier_tpu.tuning.cost_model import PRUNED_COUNTER

    counters = snapshot["counters"]
    measured = sum(
        v for k, v in counters.items()
        if k.startswith("tuning_") and k.endswith("_candidates_total")
        and k != PRUNED_COUNTER
    )
    return measured, counters.get(PRUNED_COUNTER, 0)


def axis_calls(mesh):
    """The parity capture's axis table: (axis, strategy, runner) where
    runner(cache, kw) returns the decision field. One table, so the
    tie-break retry can re-run a single axis on both caches."""
    from matvec_mpi_multiplier_tpu.tuning import search

    p = int(mesh.devices.size)
    calls = [
        ("gemv", "-", lambda cache, kw: search.tune_gemv(
            PARITY_M // p, PARITY_K, "float32", cache, **kw)["kernel"]),
        ("gemm", "-", lambda cache, kw: search.tune_gemm(
            PARITY_M // p, PARITY_K, 8, "float32", cache, **kw)["kernel"]),
    ]
    for strategy in STRATEGIES:
        calls += [
            ("combine", strategy, lambda cache, kw, s=strategy:
                search.tune_combine(
                    s, mesh, PARITY_M, PARITY_K, "float32", cache,
                    **kw)["combine"]),
            ("overlap", strategy, lambda cache, kw, s=strategy:
                search.tune_overlap(
                    s, mesh, PARITY_M, PARITY_K, "float32", cache,
                    **kw)["stages"]),
            ("storage", strategy, lambda cache, kw, s=strategy:
                search.tune_storage(
                    s, mesh, PARITY_M, PARITY_STORAGE_K, "float32", cache,
                    **kw)["storage"]),
            # Buckets start at 16: at this tiny operand the smaller
            # buckets sit at or inside the hysteresis threshold
            # (gemm ≈ (1−min_gain)·b·t_seq, with t_seq itself swinging
            # ~3× between independent sync runs on a 1-core CI host), so
            # two runs land b* anywhere in {4, 8, 16} by noise — a
            # capture artifact, not a pruning defect. b=16 clears the
            # threshold by 3–10× even at worst-case noise, so the
            # decision is reproducible; the full ladder is exercised
            # deterministically by the in-suite acceptance test.
            ("promotion", strategy, lambda cache, kw, s=strategy:
                search.tune_promotion(
                    s, mesh, PARITY_M, PARITY_K, "float32", cache,
                    buckets=(16, 32), **kw)["b_star"]),
        ]
    calls.append(("gemm_combine", "colwise", lambda cache, kw:
        search.tune_gemm_combine(
            "colwise", mesh, PARITY_M, PARITY_K, 8, "float32", cache,
            **kw)["combine"]))
    return calls


def run_axes(cache, mesh, *, prune_margin, n_reps, log, only=None,
             force=False):
    """One pass over the six tune_* axes; returns per-axis rows with the
    decision and this call's measured/pruned deltas. ``only`` restricts
    to a set of (axis, strategy) pairs (the tie-break retry);
    ``force=True`` re-measures over existing cache entries (counted by
    the stale satellite, visibly)."""
    from matvec_mpi_multiplier_tpu.obs.registry import get_registry

    rows = []
    # measure="sync" throughout: the per-rep protocol is the method of
    # record on oversubscribed virtual meshes (the loop protocol's
    # rep-spread search can stall in collective rendezvous — PR 5).
    kw = dict(n_reps=n_reps, samples=1, min_gain=PARITY_MIN_GAIN, log=log,
              prune_margin=prune_margin, measure="sync", force=force)
    for axis, strategy, runner in axis_calls(mesh):
        if only is not None and (axis, strategy) not in only:
            continue
        before = _measured_counts(get_registry().snapshot())
        decision_field = runner(cache, kw)
        after = _measured_counts(get_registry().snapshot())
        rows.append({
            "axis": axis, "strategy": strategy,
            "decision": decision_field,
            "measured": after[0] - before[0],
            "pruned": after[1] - before[1],
        })
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="data/cost_model_demo")
    ap.add_argument("--platform", default=None)
    ap.add_argument("--host-devices", type=int, default=None)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--margin", type=float, default=0.5,
                    help="prune_margin for the pruned pass")
    ap.add_argument("--n-reps", type=int, default=12)
    args = ap.parse_args(argv)

    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform

    configure_platform(args.platform, args.host_devices)

    from matvec_mpi_multiplier_tpu.obs.registry import (
        get_registry,
        reset_registry,
    )
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.tuning import search
    from matvec_mpi_multiplier_tpu.tuning.cache import (
        TuningCache,
        calibration_key,
        platform_fingerprint,
    )
    from matvec_mpi_multiplier_tpu.tuning.cost_model import (
        CostModel,
        calibrate,
        crossover_surface,
        divergence_health,
        write_surface_csv,
    )

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    mesh = make_mesh(args.devices)
    p = int(mesh.devices.size)

    print(f"== calibrating ({p}-device mesh) ==")
    cal = calibrate(mesh, level="full", n_reps=max(args.n_reps, 5))
    (out / "calibration.json").write_text(json.dumps({
        "fingerprint": platform_fingerprint(),
        "key": calibration_key(p),
        "record": cal.to_record(),
    }, indent=2) + "\n")

    print("== predicted crossover surface ==")
    rows = crossover_surface(
        CostModel(cal),
        ms=[256, 1024, 4096, 16384, 65536],
        ps=[2, 4, 8, 16, 64],
        dtypes=["float32", "bfloat16"],
    )
    write_surface_csv(rows, out / "crossover.csv")
    print(f"  {len(rows)} surface rows")

    print("== exhaustive tuning pass ==")
    reset_registry()
    ex_cache = TuningCache(out / "exhaustive_cache.json")
    ex_cache.record(calibration_key(p), cal.to_record())
    ex_rows = run_axes(
        ex_cache, mesh, prune_margin=None, n_reps=args.n_reps, log=print
    )
    ex_cache.save()

    print(f"== pruned tuning pass (margin {args.margin}) ==")
    reset_registry()
    pr_cache = TuningCache(out / "pruned_cache.json")
    pr_cache.record(calibration_key(p), cal.to_record())
    pr_rows = run_axes(
        pr_cache, mesh, prune_margin=args.margin, n_reps=args.n_reps,
        log=print,
    )

    # One deliberate hit-but-stale re-measure so the satellite counter is
    # visible in the committed snapshot (parity accounting is already
    # done; this call's candidates land only in metrics.json).
    search.tune_overlap(
        "rowwise", mesh, PARITY_M, PARITY_K, "float32", pr_cache,
        measure="sync", n_reps=args.n_reps, samples=1,
        min_gain=PARITY_MIN_GAIN, force=True, prune_margin=args.margin,
        log=print,
    )

    # Tie-break retry (the tuner's own confirmation-pass doctrine, at
    # capture scale): a near-tie can flip between two INDEPENDENT
    # measurement runs by host noise alone — that is not a pruning
    # defect, so a mismatched axis is re-raced on both caches (force=
    # True, visible in the stale counter) and only a REPRODUCED
    # disagreement fails the capture.
    for attempt in range(2):
        mismatched = {
            (ex["axis"], ex["strategy"])
            for ex, pr in zip(ex_rows, pr_rows)
            if ex["decision"] != pr["decision"]
        }
        if not mismatched:
            break
        print(f"== tie-break retry {attempt + 1}: {sorted(mismatched)} ==")
        retry_ex = run_axes(ex_cache, mesh, prune_margin=None,
                            n_reps=args.n_reps, log=print, only=mismatched,
                            force=True)
        retry_pr = run_axes(pr_cache, mesh, prune_margin=args.margin,
                            n_reps=args.n_reps, log=print, only=mismatched,
                            force=True)
        by_key_ex = {(r["axis"], r["strategy"]): r for r in retry_ex}
        by_key_pr = {(r["axis"], r["strategy"]): r for r in retry_pr}
        ex_rows = [by_key_ex.get((r["axis"], r["strategy"]), r)
                   for r in ex_rows]
        pr_rows = [by_key_pr.get((r["axis"], r["strategy"]), r)
                   for r in pr_rows]
    ex_cache.save()
    pr_cache.save()
    snapshot = get_registry().snapshot()
    (out / "metrics.json").write_text(json.dumps(snapshot, indent=2) + "\n")

    parity_rows = []
    failures = []
    for ex, pr in zip(ex_rows, pr_rows):
        assert (ex["axis"], ex["strategy"]) == (pr["axis"], pr["strategy"])
        match = ex["decision"] == pr["decision"]
        if not match:
            failures.append((ex["axis"], ex["strategy"],
                             ex["decision"], pr["decision"]))
        parity_rows.append({
            "axis": ex["axis"], "strategy": ex["strategy"],
            "decision_exhaustive": ex["decision"],
            "decision_pruned": pr["decision"],
            "match": int(match),
            "measured_exhaustive": ex["measured"],
            "measured_pruned": pr["measured"],
            "pruned": pr["pruned"],
        })
    with open(out / "prune_parity.csv", "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(parity_rows[0]))
        w.writeheader()
        w.writerows(parity_rows)

    total_ex = sum(r["measured_exhaustive"] for r in parity_rows)
    total_pr = sum(r["measured_pruned"] for r in parity_rows)
    total_skip = sum(r["pruned"] for r in parity_rows)
    health = divergence_health()
    print(f"== parity: {len(parity_rows)} axis rows, "
          f"{total_ex} -> {total_pr} measured "
          f"({1 - total_pr / total_ex:.0%} fewer, {total_skip} pruned), "
          f"divergence {health['median_abs_log10_ratio']:.3f} ==")
    if failures:
        print(f"PARITY FAILURE: {failures}", file=sys.stderr)
        return 1
    if total_pr > 0.6 * total_ex:
        print(f"SAVINGS FAILURE: only {1 - total_pr / total_ex:.0%} fewer "
              "candidates (need >= 40%)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

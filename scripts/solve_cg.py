#!/usr/bin/env python
"""Distributed Krylov solver CLI (models/cg.py, models/gmres.py).

Solves ``A x = b`` with the matrix sharded by any strategy (never
replicated) and one compiled ``lax.while_loop`` driving the iteration —
the framework's distributed matvec running inside a real Krylov solver
instead of a benchmark harness. ``--method cg`` (default) assumes SPD A;
``--method gmres`` runs restarted GMRES on a deliberately NONSYMMETRIC
system, the general-matrix case CG cannot touch.

Examples::

    python scripts/solve_cg.py --size 1024 --strategy blockwise
    python scripts/solve_cg.py --size 1024 --method gmres --restart 40
    python scripts/solve_cg.py --size 1024 --kernel ozaki --tol 1e-10 \
        --platform cpu --host-devices 8
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--size", type=int, default=1024,
                   help="n for the n x n system")
    p.add_argument("--strategy", default="blockwise")
    p.add_argument("--method", choices=["cg", "gmres"], default="cg",
                   help="cg: SPD systems; gmres: general (nonsymmetric) "
                   "systems via restarted CGS2-Arnoldi")
    p.add_argument("--restart", type=int, default=40,
                   help="GMRES(m) basis size (ignored for cg)")
    p.add_argument("--kernel", default="xla",
                   help="local GEMV tier (xla | pallas | compensated | "
                   "ozaki | ... — the fp64-parity tiers matter for "
                   "ill-conditioned systems)")
    p.add_argument("--tol", type=float, default=1e-6,
                   help="relative tolerance: stop at ||r|| <= tol * ||b||")
    p.add_argument("--max-iters", type=int, default=None,
                   help="cg iteration cap (default 1000; cg-only — gmres "
                   "is bounded by --max-restarts)")
    p.add_argument("--max-restarts", type=int, default=50,
                   help="GMRES outer-cycle cap (ignored for cg)")
    p.add_argument("--precondition", choices=["none", "jacobi"],
                   default="none",
                   help="jacobi: diag(A) preconditioner — the cheap win "
                   "when rows live on very different scales")
    p.add_argument("--refine", action="store_true",
                   help="mixed-precision iterative refinement: fp32 "
                   "corrections by the chosen --method (CG or GMRES) + "
                   "fp64-parity (ozaki) residuals + double-float x — "
                   "~fp32-ulp solutions where plain fp32 CG floors at "
                   "cond(A)*eps, and past the fp32 residual-evaluation "
                   "floor for GMRES")
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None,
                   help="jax platform override (e.g. cpu; the env var alone "
                   "is outranked by the preinstalled accelerator plugin's "
                   "jax.config pin)")
    p.add_argument("--host-devices", type=int, default=None,
                   help="virtual CPU device count (the mpiexec -n analog)")
    args = p.parse_args(argv)

    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform

    configure_platform(args.platform, args.host_devices)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from matvec_mpi_multiplier_tpu import get_strategy, make_mesh
    from matvec_mpi_multiplier_tpu.models.cg import build_cg, build_refined
    from matvec_mpi_multiplier_tpu.models.gmres import build_gmres
    from matvec_mpi_multiplier_tpu.parallel import distributed

    distributed.initialize()
    mesh = make_mesh(args.devices)
    n = args.size
    rng = np.random.default_rng(args.seed)
    g = rng.standard_normal((n, n)).astype(np.float32)
    if args.method == "gmres":
        if args.precondition != "none" or args.max_iters is not None:
            p.error("--precondition/--max-iters are cg-only options "
                    "(gmres is bounded by --max-restarts)")
        # Deliberately nonsymmetric, spectrum shifted off the origin —
        # the system class GMRES exists for and CG would diverge on.
        a_host = (g / np.sqrt(n) + 2.0 * np.eye(n, dtype=np.float32))
        a_host = a_host.astype(np.float32)
    else:
        # SPD by construction: G'G/n + I (well-conditioned; --kernel's
        # accuracy tiers earn their keep as conditioning worsens, not
        # here).
        a_host = (g.T @ g / n + np.eye(n, dtype=np.float32)).astype(
            np.float32
        )
    x_true = rng.standard_normal(n).astype(np.float32)
    b_host = a_host @ x_true

    strategy = get_strategy(args.strategy)
    precondition = False if args.precondition == "none" else args.precondition
    max_iters = 1000 if args.max_iters is None else args.max_iters
    if args.method == "gmres" and args.refine:
        # Nonsymmetric mixed-precision refinement: fp32 GMRES corrections,
        # fp64-parity residuals, double-float x (build_refined inner=gmres).
        run = build_refined(
            strategy, mesh, inner="gmres", kernel=args.kernel, tol=args.tol,
            restart=args.restart, max_restarts=args.max_restarts,
        )
        label = f"{args.kernel}/gmres({args.restart})+refine(ozaki)"
    elif args.method == "gmres":
        run = build_gmres(
            strategy, mesh, kernel=args.kernel, tol=args.tol,
            restart=args.restart, max_restarts=args.max_restarts,
        )
        label = f"{args.kernel}/gmres({args.restart})"
    elif args.refine:
        # Built ONCE: the compiled inner-CG and residual programs are
        # reused by the timed second call (--kernel drives the inner CG;
        # the residual always runs the fp64-parity ozaki tier).
        run = build_refined(
            strategy, mesh, kernel=args.kernel, tol=args.tol,
            max_iters=max_iters, precondition=precondition,
        )
        label = f"{args.kernel}+refine(ozaki)"
    else:
        run = build_cg(
            strategy, mesh, kernel=args.kernel, tol=args.tol,
            max_iters=max_iters, precondition=precondition,
        )
        label = args.kernel
    # Device-resident operands OUTSIDE the timed region: the reported ms
    # is the solve, not an n^2 host->device transfer (the amortized-mode
    # stance of bench/timing.py).
    a_dev = jnp.asarray(a_host)
    b_dev = jnp.asarray(b_host)
    res = run(a_dev, b_dev)  # compile + run
    jax.block_until_ready(res.x)
    t0 = time.perf_counter()
    res = run(a_dev, b_dev)
    jax.block_until_ready(res.x)
    dt = time.perf_counter() - t0

    err = float(np.max(np.abs(np.asarray(res.x) - x_true)))
    if distributed.is_main_process():
        print(
            f"{args.method}[{args.strategy}/{label}] n={n} "
            f"p={mesh.devices.size}: "
            f"converged={bool(res.converged)} iters={int(res.n_iters)} "
            f"||r||={float(res.residual_norm):.3e} max|x-x_true|={err:.3e} "
            f"{dt * 1e3:.1f} ms"
        )
    return 0 if bool(res.converged) else 1


if __name__ == "__main__":
    sys.exit(main())

#!/bin/sh
# Tier-1 verify wrapper: the ROADMAP.md tier-1 command plus the repo's
# static-analysis gate, as one entry point for CI and local runs.
#
#   ./scripts/tier1.sh            # lint + tier-1 test suite
#   ./scripts/tier1.sh --lint-only
#
# Lint is the staticcheck AST rule layer (matvec_mpi_multiplier_tpu/
# staticcheck — rule catalogue in docs/STATIC_ANALYSIS.md): shard_map only
# via utils/compat.py, no host syncs on the engine dispatch path, no
# full-width collectives in staged-overlap bodies, no blocking I/O on the
# dispatch hot path, no implicit fp64 promotion / import-time jnp work /
# mutable default arguments — PLUS the whole-program lock-graph
# concurrency auditor (rules #13-#15: mixed guard access, lock-order
# inversion cycles, callback-under-lock; staticcheck/lockgraph.py is
# pure AST, so it rides --rules inside the lint budget). The same engine
# backs tests/test_lint.py in-suite; this wrapper lets CI fail fast
# before spending the full suite's runtime. --rules skips the
# lowered-HLO schedule + compiled-artifact memory audits (which need the
# 8-device CPU mesh, and ride the suite via tests/test_staticcheck.py)
# — the rule layer never initializes a device backend (package import
# still pulls jax in; ~1 s total), keeping --lint-only well under its
# 10-second budget. Exit codes: 1 rule findings, 3 HLO-audit failures,
# 4 golden drift (set -e fails this script on any of them).

set -eu
cd "$(dirname "$0")/.."

# The rule layer rides inside a hard latency budget: the whole point of
# a pure-AST tier (no jax import — the package re-exports are lazy, no
# device backend, content-hashed whole-program caches) is that it runs
# on every edit. A wall-clock regression here means someone taxed the
# hot path; fail loudly instead of letting the lint tier quietly decay
# into a suite-speed tool. Python's own perf_counter, not `time`(1):
# POSIX sh offers no portable sub-second arithmetic.
lint_t0=$(python -c 'import time; print(time.perf_counter())')
python -m matvec_mpi_multiplier_tpu.staticcheck --rules
python - "$lint_t0" <<'PY'
import sys, time
elapsed = time.perf_counter() - float(sys.argv[1])
budget = 3.0
print(f"lint wall-clock: {elapsed:.2f}s (budget {budget:.0f}s)")
if elapsed >= budget:
    sys.exit(f"--rules took {elapsed:.2f}s, over the {budget:.0f}s "
             "tier-1 budget (did a rule start importing jax or "
             "re-walking the corpus per rule?)")
PY

# Keyspace smoke: the symbolic ExecKey-space audit (enumeration vs the
# committed golden + the steady-subset-of-warmup compile budget) is
# jax-free and sub-second, so it rides the lint tier — a widened compile
# surface or an unwarmed steady key fails here before the suite spends
# runtime proving compiles_steady == 0 dynamically.
python -m matvec_mpi_multiplier_tpu.staticcheck --keyspace
[ "${1:-}" = "--lint-only" ] && exit 0

# Chaos smoke: one seeded --fault-spec serve trace end-to-end through the
# real CLI (engine + scheduler + FaultPlan + retry policy + availability
# columns). Deterministic (hash-derived injection draws) and small — a
# regression here means the resilience stack cannot even start, which
# should fail fast before the full suite spends its runtime.
echo "chaos smoke: seeded fault-injection serve trace"
python -m matvec_mpi_multiplier_tpu.bench.serve \
    --strategy rowwise --sizes 64 --devices 8 \
    --platform cpu --host-devices 8 \
    --concurrency 4 --coalesce on --n-requests 24 --max-bucket 8 \
    --fault-spec "dispatch:device_error:p=0.2" --fault-seed 3 --no-csv

# Quantized smoke: small-shape compensated-int8 vs native through a real
# distributed build — the storage axis must clear its own fp32-level
# error budget (ops/quantize.py constants; docs/QUANTIZATION.md) before
# the suite spends runtime on the full gate in tests/test_quantized.py.
echo "quantized smoke: int8c residual within the fp32-level budget"
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'PY'
import numpy as np, jax
from matvec_mpi_multiplier_tpu import get_strategy, make_mesh
from matvec_mpi_multiplier_tpu.ops.quantize import (
    FP32_LEVEL_RELERR, quantize_matrix,
)

mesh = make_mesh(8)
strat = get_strategy("colwise")
rng = np.random.default_rng(0)
a = rng.standard_normal((32, 1024)).astype(np.float32)
x = rng.standard_normal(1024).astype(np.float32)
sh_a, sh_x = strat.shardings(mesh)
x_dev = jax.device_put(x, sh_x)
y_native = np.asarray(
    strat.build(mesh)(jax.device_put(a, sh_a), x_dev)
)
qa = quantize_matrix(
    a, "int8c", contraction_shards=strat.contraction_shards(mesh)
)
y_quant = np.asarray(
    strat.build(mesh, dtype_storage="int8c")(
        jax.device_put(qa, sh_a), x_dev
    )
)
rel = np.abs(y_quant - y_native).max() / np.abs(y_native).max()
assert rel <= FP32_LEVEL_RELERR, (
    f"int8c vs native relerr {rel:.3e} over {FP32_LEVEL_RELERR:.0e}"
)
assert qa.nbytes <= 0.55 * a.nbytes
print(f"quantized smoke ok: relerr {rel:.2e}, "
      f"bytes {qa.nbytes / a.nbytes:.3f}x")
PY

# Multi-tenant eviction smoke: 3 tenants against a budget that holds 2 —
# the registry must swap (evictions observed), keep the ledger inside the
# budget, and re-admit evicted tenants BITWISE-identically
# (engine/registry.py; docs/MULTITENANT.md). Seconds, not minutes: a
# regression here means multi-tenant serving cannot even start.
echo "multi-tenant smoke: eviction + bitwise re-admission under budget"
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'PY'
import numpy as np
from matvec_mpi_multiplier_tpu import MatrixRegistry, make_mesh

mesh = make_mesh(8)
rng = np.random.default_rng(0)
mats = {f"t{i}": rng.standard_normal((64, 64)).astype(np.float32)
        for i in range(3)}
payload = 64 * 64 * 4
x = rng.standard_normal(64).astype(np.float32)

reg = MatrixRegistry(mesh, hbm_budget=2 * payload, strategy="rowwise",
                     promote=None)
handles = {tid: reg.register(tid, a) for tid, a in mats.items()}
reg.warmup(widths=[1])
first = {tid: handles[tid](x) for tid in mats}   # third admission evicts
h = reg.health()
assert h["hbm"]["charged_bytes"] <= 2 * payload, h["hbm"]
evicted = [t for t, s in h["tenants"].items() if not s["resident"]]
assert len(evicted) == 1, h["tenants"]
again = handles[evicted[0]](x)                   # swap back in
assert np.array_equal(again, first[evicted[0]]), "re-admit not bitwise"
total_evictions = sum(s["evictions"] for s in h["tenants"].values())
assert total_evictions >= 1
reg.close()
print(f"multi-tenant smoke ok: {total_evictions} eviction(s), "
      f"re-admit bitwise, ledger {h['hbm']['charged_bytes']} <= "
      f"{2 * payload}")
PY

# Cost-model smoke: a quick 2-probe calibration, then ONE tuning axis
# run twice — exhaustive vs prune_margin — through the real measurement
# path (tuning/cost_model.py + search.py; docs/COST_MODEL.md). Pruned
# tuning must reach the exhaustive decision while measuring strictly
# fewer candidates, with every pruned candidate logged. Seconds, not
# minutes: a regression here means predicted-time pruning cannot even
# start, which should fail fast before the full suite runs the
# fake-timer acceptance gate in tests/test_cost_model.py.
echo "cost-model smoke: pruned == exhaustive decision on the overlap axis"
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'PY'
import tempfile
from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
from matvec_mpi_multiplier_tpu.tuning import search
from matvec_mpi_multiplier_tpu.tuning.cache import (
    TuningCache, calibration_key,
)
from matvec_mpi_multiplier_tpu.tuning.cost_model import calibrate

mesh = make_mesh(8)
cal = calibrate(mesh, level="quick", n_reps=3, log=lambda *_: None)
tmp = tempfile.mkdtemp()
kw = dict(measure="sync", n_reps=2, samples=1, min_gain=0.25)
ex = TuningCache(f"{tmp}/ex.json")
ex.record(calibration_key(8), cal.to_record())
d1 = search.tune_overlap("rowwise", mesh, 64, 64, "float32", ex,
                         log=lambda *_: None, **kw)
pr = TuningCache(f"{tmp}/pr.json")
pr.record(calibration_key(8), cal.to_record())
logs = []
d2 = search.tune_overlap("rowwise", mesh, 64, 64, "float32", pr,
                         prune_margin=0.5, log=logs.append, **kw)
assert d1["stages"] == d2["stages"], (d1, d2)
assert len(d2["candidates"]) < len(d1["candidates"]), (d1, d2)
assert d2["pruned"], d2
assert sum(": pruned (" in line for line in logs) == len(d2["pruned"])
print(f"cost-model smoke ok: pruned {len(d2['pruned'])} of "
      f"{len(d1['candidates'])} candidates, same decision "
      f"S={d1['stages']}")
PY

# Global-scheduler smoke: a 2-probe quick calibration, then a synthetic
# overload burst through the real admission path (engine/
# global_scheduler.py + tuning/cost_model.py; docs/SCHEDULING.md). The
# scheduler must reject-fast at least once (typed, pre-dispatch, with a
# prediction on the decision) and the engines' deadline-expire counter
# must stay at ZERO — the failure mode predicted-time admission exists
# to delete. Seconds, not minutes: a regression here means SLO-aware
# scheduling cannot even start.
echo "global-scheduler smoke: reject-fast under a synthetic overload burst"
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'PY'
import numpy as np
from matvec_mpi_multiplier_tpu import make_mesh
from matvec_mpi_multiplier_tpu.engine import GlobalScheduler, MatrixRegistry
from matvec_mpi_multiplier_tpu.tuning.cost_model import CostModel, calibrate
from matvec_mpi_multiplier_tpu.utils.errors import AdmissionRejectedError

mesh = make_mesh(8)
model = CostModel(calibrate(mesh, level="quick", n_reps=3,
                            log=lambda *_: None))
rng = np.random.default_rng(0)
reg = MatrixRegistry(mesh, strategy="rowwise", promote=None,
                     demand_weight=2.0)
for i in range(2):
    reg.register(f"t{i}", rng.standard_normal((64, 64)).astype(np.float32))
gs = GlobalScheduler(reg, cost_model=model)
x = rng.standard_normal(64).astype(np.float32)
served = rejected = 0
# The burst: loose-deadline requests serve; sub-dispatch-time deadlines
# CANNOT be met and must be rejected at the door, never queued to expire.
for j in range(24):
    fut = gs.submit(f"t{j % 2}", x,
                    deadline_ms=1e6 if j % 3 == 0 else 1e-4)
    if isinstance(fut.exception(), AdmissionRejectedError):
        rejected += 1
    else:
        gs.flush()
        assert fut.result().shape == (64,)
        served += 1
decisions = gs.decisions()
assert rejected >= 1, "overload burst produced no reject-fast"
assert served >= 1, "admission rejected everything"
for d in decisions:
    assert "predicted_s" in d and "reason" in d, d
rejects = [d for d in decisions if d["decision"] == "reject"]
assert rejects and all(d["predicted_s"] is not None for d in rejects)
expires = reg.metrics.counter("engine_deadline_failures_total").value
assert expires == 0, f"{expires} requests expired in an engine gate"
gs.close(); reg.close()
print(f"global-scheduler smoke ok: {served} served, {rejected} "
      f"rejected fast with predictions, 0 deadline-expires")
PY

# Served-solver smoke: engine.submit(op="cg") on a small seeded SPD
# operand (solvers/; docs/SOLVERS.md) — convergence against the host
# residual, a rtol/maxiter sweep sharing ONE compiled loop
# (compiles_steady == 0, the knobs are dynamic operands), and the typed
# SolverDivergedError contract on a starved cap. Seconds, not minutes: a
# regression here means serving answers cannot even start, which should
# fail fast before the suite runs the full gate in tests/test_solvers.py.
echo "solver smoke: served CG converges compile-flat, diverges typed"
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'PY'
import numpy as np
from matvec_mpi_multiplier_tpu import MatvecEngine, make_mesh
from matvec_mpi_multiplier_tpu.bench.serve import solver_operand
from matvec_mpi_multiplier_tpu.utils.errors import SolverDivergedError

mesh = make_mesh(8)
a = solver_operand(128, "float32", seed=0)
engine = MatvecEngine(a, mesh, strategy="rowwise", promote=None)
rng = np.random.default_rng(1)
b0 = rng.standard_normal(128).astype(np.float32)
res = engine.submit(op="cg", rhs=b0, rtol=1e-5).result()
assert res.converged and res.n_iters >= 1
relres = np.linalg.norm(b0 - a @ res.x) / np.linalg.norm(b0)
assert relres <= 1e-4, f"host residual {relres:.2e}"
compiles = engine.stats.compiles
for i in range(6):  # sweep the dynamic knobs: same executable
    b = rng.standard_normal(128).astype(np.float32)
    r = engine.submit(op="cg", rhs=b, rtol=(1e-3, 1e-5)[i % 2],
                      maxiter=(200, 1000)[i % 2]).result()
    assert r.converged
assert engine.stats.compiles == compiles, "solver knob sweep recompiled"
try:
    engine.submit(op="cg", rhs=b0, rtol=1e-7, maxiter=2).result()
except SolverDivergedError:
    pass
else:
    raise AssertionError("starved cap did not raise SolverDivergedError")
divergences = engine.metrics.counter("solver_divergences_total").value
assert divergences == 1, divergences
print(f"solver smoke ok: cg relres {relres:.2e} in {res.n_iters} iters, "
      f"{compiles} compile(s) across the sweep, 1 typed divergence")
PY

# Fused-solver smoke: the pallas_fused iteration tier (interpret mode on
# CPU; ops/pallas_solver.py, docs/SOLVERS.md "Fused iteration tier")
# against the XLA tier through the ONE shared constructor. rtol=0 pins
# both programs to exactly maxiter while-body iterations, so the two
# residual TRAJECTORIES are compared point-for-point — a fused body that
# drifts from the reference recurrence fails here in seconds, before the
# full parity gate in tests/test_solvers.py.
echo "fused-solver smoke: pallas_fused trajectory matches the XLA tier"
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'PY'
import jax
import jax.numpy as jnp
import numpy as np
from matvec_mpi_multiplier_tpu import make_mesh
from matvec_mpi_multiplier_tpu.bench.serve import solver_operand
from matvec_mpi_multiplier_tpu.models import get_strategy
from matvec_mpi_multiplier_tpu.solvers import build_solver

mesh = make_mesh(8)
n = 96
a = solver_operand(n, "float32", seed=0)
b = np.random.default_rng(1).standard_normal(n).astype(np.float32)
strat = get_strategy("rowwise")
fns = {
    kern: jax.jit(build_solver("cg", strat, mesh, dtype=jnp.float32,
                               kernel=kern))
    for kern in ("xla", "pallas_fused")
}
traj = {kern: [] for kern in fns}
for k in (1, 2, 4, 8):  # fixed-iteration ladder: rtol=0 never fires
    for kern, fn in fns.items():
        res = fn(a, b, jnp.float32(0.0), jnp.int32(k),
                 jnp.float32(0.0), jnp.float32(0.0))
        assert int(res.n_iters) == k, (kern, k, int(res.n_iters))
        traj[kern].append(float(np.linalg.norm(b - a @ np.asarray(res.x))))
xla_t, fused_t = np.array(traj["xla"]), np.array(traj["pallas_fused"])
assert np.all(np.diff(xla_t) < 0), f"xla residuals not decreasing: {xla_t}"
assert np.allclose(fused_t, xla_t, rtol=5e-3, atol=1e-6), (
    f"fused trajectory drifts from XLA: {fused_t} vs {xla_t}")
conv = {
    kern: fn(a, b, jnp.float32(1e-5), jnp.int32(400),
             jnp.float32(0.0), jnp.float32(0.0))
    for kern, fn in fns.items()
}
assert all(bool(r.converged) for r in conv.values())
assert int(conv["xla"].n_iters) == int(conv["pallas_fused"].n_iters)
print(f"fused-solver smoke ok: trajectories agree over {len(xla_t)} "
      f"ladder points, both tiers converge in "
      f"{int(conv['xla'].n_iters)} iters")
PY

# Speculative smoke: both verdicts of the two-tier dispatch through a
# real 8-device distributed build (ops/speculative.py + engine rtol
# routing; docs/QUANTIZATION.md "speculative serving"). A well-
# conditioned request must be served from the int8c tier WITHOUT
# escalating; a cancellation-built adversarial operand must fail the
# on-device check and escalate to the bitwise-native answer — the
# escalation counter is asserted both ways, so a check that always
# accepts OR always rejects fails here in seconds.
echo "speculative smoke: int8c accept + forced escalation, counter both ways"
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'PY'
import numpy as np
from matvec_mpi_multiplier_tpu import MatvecEngine, make_mesh

mesh = make_mesh(8)
rng = np.random.default_rng(0)
a_ok = rng.uniform(0.0, 10.0, (64, 256)).astype(np.float32)
x_ok = rng.uniform(0.0, 10.0, 256).astype(np.float32)

clean = MatvecEngine(a_ok, mesh, strategy="rowwise", promote=None,
                     dtype_storage="speculate")
y = clean.submit(x_ok, rtol=1e-3).result()
oracle = a_ok.astype(np.float64) @ x_ok.astype(np.float64)
rel = np.linalg.norm(y - oracle) / np.linalg.norm(oracle)
assert rel <= 1e-3, f"accepted candidate off budget: {rel:.2e}"
h = clean.health()
assert h["counters"]["speculative_dispatches"] == 1, h["counters"]
assert h["counters"]["escalations"] == 0, "clean operand escalated"

# Catastrophic cancellation: Ax ~ 0 while the int8c grid error stays at
# the grid scale, so the candidate's RELATIVE error explodes.
a_bad = rng.standard_normal((64, 256)).astype(np.float64)
x_bad = rng.standard_normal(256).astype(np.float64)
a_bad -= np.outer(a_bad @ x_bad, x_bad) / float(x_bad @ x_bad)
a_bad, x_bad = a_bad.astype(np.float32), x_bad.astype(np.float32)
spec = MatvecEngine(a_bad, mesh, strategy="rowwise", promote=None,
                    dtype_storage="speculate")
plain = MatvecEngine(a_bad, mesh, strategy="rowwise", promote=None)
y_bad = spec.submit(x_bad, rtol=1e-3).result()
h = spec.health()
assert h["counters"]["escalations"] == 1, "adversarial operand accepted"
assert np.array_equal(y_bad, plain.submit(x_bad).result()), (
    "escalated answer != native answer"
)
print(f"speculative smoke ok: accept relerr {rel:.2e}, escalation "
      f"rate {h['storage']['escalation_rate']:.1f} on the adversary, "
      "escalated answer bitwise-native")
PY

# Reshard smoke: one on-device rowwise→blockwise migration of a resident
# A (parallel/reshard.py + engine swap fence; docs/RESHARDING.md). The
# migrated engine must answer BITWISE-identically to a fresh registration
# in the destination layout, the residency ledger must stay balanced
# through the migration (footprint-neutral: the collectives replace the
# payload in place), and after the one-time new-layout compile (ridden
# in by warm_widths) steady requests must never recompile. Seconds, not
# minutes: a regression here means online resharding cannot even start,
# which should fail fast before the full gate in tests/test_reshard.py.
echo "reshard smoke: rowwise->blockwise bitwise, ledger balanced, compile-flat"
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'PY'
import numpy as np
from matvec_mpi_multiplier_tpu import MatvecEngine, make_mesh

mesh = make_mesh(8)
rng = np.random.default_rng(0)
a = rng.standard_normal((64, 512)).astype(np.float32)
x = rng.standard_normal(512).astype(np.float32)
ledger = [0]
eng = MatvecEngine(
    a, mesh, strategy="rowwise", promote=None,
    residency_listener=lambda delta, reason: ledger.__setitem__(
        0, ledger[0] + delta
    ),
)
eng.submit(x).result()  # place + serve in the source layout
assert ledger[0] == eng.device_resident_bytes, "ledger off pre-migration"
res = eng.reshard("blockwise", warm_widths=(1,))
assert res["migrated"] and not res["aborted"], res
assert res["bytes_moved"] == a.nbytes, res
assert ledger[0] == eng.device_resident_bytes, (
    "migration leaked in the residency ledger"
)
fresh = MatvecEngine(a, mesh, strategy="blockwise", promote=None)
y_fresh = fresh.submit(x).result()
assert np.array_equal(eng.submit(x).result(), y_fresh), (
    "migrated answer != fresh destination registration"
)
warm = eng.stats.compiles  # new-layout compile rode in via warm_widths
for _ in range(4):
    assert np.array_equal(eng.submit(x).result(), y_fresh)
assert eng.stats.compiles == warm, "steady requests recompiled"
eng.close(); fresh.close()
print(f"reshard smoke ok: {res['src']}->{res['dst']} bitwise vs fresh, "
      f"{res['bytes_moved']} bytes moved ledger-neutral, "
      "0 steady recompiles")
PY

# SLO/flight smoke: the committed observability capture (data/slo_demo;
# scripts/slo_study.py; docs/OBSERVABILITY.md) still tells its story —
# the burn-rate page alert is in slo.json, every flight dump was
# triggered by a typed failure and carries a correlated event ring, and
# `obs timeline` reconstructs the committed failed request end-to-end
# (admission, retries, the typed failure) with every event carrying its
# correlation id. One interpreter, no engine: seconds, not minutes.
echo "slo/flight smoke: page alert, typed-failure dump, correlated timeline"
JAX_PLATFORMS=cpu python - <<'PY'
import json
from pathlib import Path

from matvec_mpi_multiplier_tpu.obs import FAILURE_KINDS, related_events
from matvec_mpi_multiplier_tpu.obs.__main__ import render_timeline

demo = Path("data/slo_demo")
slo = json.loads((demo / "slo.json").read_text())
pages = [a for a in slo["alerts"] if a["severity"] == "page"]
assert pages, f"no page alert in committed slo.json: {slo['alerts']}"
assert slo["targets"][pages[0]["slo"]]["status"] == "page"
dumps = sorted(demo.glob("flight/flight_*.json"))
assert dumps, "no committed flight dump"
for p in dumps:
    bundle = json.loads(p.read_text())
    assert bundle["trigger"]["kind"] in FAILURE_KINDS, p.name
    assert all(
        "request_id" in ev or "cause_id" in ev for ev in bundle["events"]
    ), p.name
events = [
    json.loads(line)
    for line in (demo / "events.jsonl").read_text().splitlines()
]
assert all("request_id" in ev or "cause_id" in ev for ev in events), (
    "an event line is missing its correlation id"
)
rid = json.loads((demo / "summary.json").read_text())["failed_request_id"]
kinds = {ev["kind"] for ev in related_events(events, rid)}
assert kinds & FAILURE_KINDS and {"submit", "retry"} <= kinds, kinds
head = render_timeline(events, rid).splitlines()[0]
assert head.startswith(f"request {rid}:") and "failure" in head, head
print(f"slo/flight smoke ok: {pages[0]['slo']} page at "
      f"{pages[0]['burn_short']:.0f}x/{pages[0]['burn_long']:.0f}x burn, "
      f"{len(dumps)} typed-failure dump(s), timeline for request {rid} "
      f"({head.split(': ')[1]})")
PY

# ROADMAP.md tier-1 verify command (kept in sync with the ROADMAP header).
# Portability note: under /bin/sh without pipefail (dash), `rc=$?` after
# `pytest | tee` reads TEE's status, so a failing suite could exit 0. The
# status file captures pytest's (or timeout's) real status from inside the
# pipeline's left-hand subshell instead — via `|| echo $?`, which is also
# exempt from errexit (a bare failing pytest would kill that subshell
# under `set -e` before any capture ran). pipefail, where supported,
# additionally covers a tee failure — probed in a subshell, because dash
# treats `set -o pipefail` as a special-builtin error and exits the whole
# script even behind `|| true`.
if (set -o pipefail) 2>/dev/null; then set -o pipefail; fi
rm -f /tmp/_t1.log
# Private rc file (mktemp): a fixed /tmp name would let two concurrent
# tier-1 runs cross-contaminate exit codes. /tmp/_t1.log stays fixed —
# it is the ROADMAP tier-1 command's own convention.
rc_file=$(mktemp /tmp/_t1_rc.XXXXXX)
echo 0 > "$rc_file"
{
  timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 || echo $? > "$rc_file"
} | tee /tmp/_t1.log
rc=$(cat "$rc_file")
rm -f "$rc_file"
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit "$rc"

#!/bin/sh
# Tier-1 verify wrapper: the ROADMAP.md tier-1 command plus the repo lint
# gate, as one entry point for CI and local runs.
#
#   ./scripts/tier1.sh            # lint + tier-1 test suite
#   ./scripts/tier1.sh --lint-only
#
# Lint: direct `jax.shard_map` / `jax.experimental.shard_map` references are
# forbidden outside utils/compat.py — every module goes through the
# cross-version shim so a JAX API bump is a one-file change. (The same rule
# is enforced in-suite by tests/test_lint.py; this wrapper lets CI fail fast
# before spending the full suite's runtime.)

set -eu
cd "$(dirname "$0")/.."

lint() {
  # --include limits the sweep to Python sources; compat.py is the one
  # allowed importer. Matches attribute use AND both import spellings.
  bad=$(grep -rnE \
      'jax\.shard_map|jax\.experimental\.shard_map|from jax\.experimental import shard_map' \
      --include='*.py' \
      matvec_mpi_multiplier_tpu tests scripts bench.py __graft_entry__.py \
      2>/dev/null | grep -v 'matvec_mpi_multiplier_tpu/utils/compat\.py' || true)
  if [ -n "$bad" ]; then
    echo "LINT: direct shard_map references outside utils/compat.py:" >&2
    echo "$bad" >&2
    echo "Route them through matvec_mpi_multiplier_tpu.utils.compat." >&2
    return 1
  fi
  echo "lint: ok (no direct shard_map references outside utils/compat.py)"
}

lint
[ "${1:-}" = "--lint-only" ] && exit 0

# ROADMAP.md tier-1 verify command (kept in sync with the ROADMAP header).
set -o pipefail 2>/dev/null || true
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
  -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=$?
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit $rc

#!/bin/sh
# Tier-1 verify wrapper: the ROADMAP.md tier-1 command plus the repo lint
# gate, as one entry point for CI and local runs.
#
#   ./scripts/tier1.sh            # lint + tier-1 test suite
#   ./scripts/tier1.sh --lint-only
#
# Lint: direct `jax.shard_map` / `jax.experimental.shard_map` references are
# forbidden outside utils/compat.py — every module goes through the
# cross-version shim so a JAX API bump is a one-file change. (The same rule
# is enforced in-suite by tests/test_lint.py; this wrapper lets CI fail fast
# before spending the full suite's runtime.)

set -eu
cd "$(dirname "$0")/.."

lint() {
  # --include limits the sweep to Python sources; compat.py is the one
  # allowed importer. Matches attribute use AND both import spellings.
  bad=$(grep -rnE \
      'jax\.shard_map|jax\.experimental\.shard_map|from jax\.experimental import shard_map' \
      --include='*.py' \
      matvec_mpi_multiplier_tpu tests scripts bench.py __graft_entry__.py \
      2>/dev/null | grep -v 'matvec_mpi_multiplier_tpu/utils/compat\.py' || true)
  if [ -n "$bad" ]; then
    echo "LINT: direct shard_map references outside utils/compat.py:" >&2
    echo "$bad" >&2
    echo "Route them through matvec_mpi_multiplier_tpu.utils.compat." >&2
    return 1
  fi
  echo "lint: ok (no direct shard_map references outside utils/compat.py)"

  # Engine dispatch paths must never host-sync (the async submit contract):
  # block_until_ready / device_get / materializing asarray are forbidden in
  # engine/ except on lines whose `# sync-ok: <reason>` marker documents a
  # deliberate materialization point (future.result, one-time host staging).
  # Timing code is exempt by living in bench/serve.py. (Same rule in-suite:
  # tests/test_lint.py::test_no_host_syncs_in_engine_dispatch.)
  bad=$(grep -rnE \
      'block_until_ready|device_get|np\.asarray|np\.array\(|jnp\.asarray' \
      --include='*.py' matvec_mpi_multiplier_tpu/engine \
      2>/dev/null | grep -v 'sync-ok:' || true)
  if [ -n "$bad" ]; then
    echo "LINT: host syncs in engine/ dispatch paths:" >&2
    echo "$bad" >&2
    echo "Mark deliberate materialization points with '# sync-ok: <reason>'" >&2
    echo "or move timing code to bench/serve.py." >&2
    return 1
  fi
  echo "lint: ok (no unmarked host syncs in engine/ dispatch paths)"

  # Overlap schedule bodies must stay chunked: a full-width all_gather or
  # psum inside the staged-overlap/collective-kernel modules would serialize
  # the very communication the schedule exists to hide. Deliberate chunked
  # uses (e.g. the per-stage psum over grid columns) carry an
  # `# overlap-ok: <reason>` marker. (Same rule in-suite:
  # tests/test_lint.py::test_no_unchunked_collectives_in_overlap_bodies.)
  bad=$(grep -rnE \
      'jax\.lax\.all_gather\(|jax\.lax\.psum\(' \
      --include='*.py' \
      matvec_mpi_multiplier_tpu/parallel/ring.py \
      matvec_mpi_multiplier_tpu/ops/pallas_collective.py \
      2>/dev/null | grep -v 'overlap-ok:' || true)
  if [ -n "$bad" ]; then
    echo "LINT: un-chunked full-width collectives in overlap schedule bodies:" >&2
    echo "$bad" >&2
    echo "Stage the collective (1/S of the bytes per issue) or mark a" >&2
    echo "deliberate chunked use with '# overlap-ok: <reason>'." >&2
    return 1
  fi
  echo "lint: ok (no un-chunked collectives in overlap schedule bodies)"

  # The engine dispatch hot path (engine/ plus the obs in-memory layer)
  # must never block on file I/O: a file write or json.dump inside submit
  # would stall every request behind the filesystem — the reason the trace
  # sink is a separate thread. Exempt by name: obs/sink.py (the sink
  # thread — the ONE place obs touches files) and obs/__main__.py (the
  # CLI, driver code). Deliberate exceptions elsewhere carry an
  # `# obs-ok: <reason>` marker. (Same rule in-suite:
  # tests/test_lint.py::test_no_blocking_io_on_dispatch_hot_path.)
  bad=$(grep -rnE \
      '\bopen\(|json\.dump|\.write\(|write_text\(|write_bytes\(' \
      --include='*.py' \
      matvec_mpi_multiplier_tpu/engine matvec_mpi_multiplier_tpu/obs \
      2>/dev/null \
      | grep -v 'matvec_mpi_multiplier_tpu/obs/sink\.py' \
      | grep -v 'matvec_mpi_multiplier_tpu/obs/__main__\.py' \
      | grep -v 'obs-ok:' || true)
  if [ -n "$bad" ]; then
    echo "LINT: blocking I/O on the engine dispatch hot path:" >&2
    echo "$bad" >&2
    echo "Route file writes through the obs sink thread (obs/sink.py) or" >&2
    echo "mark a deliberate non-hot-path write with '# obs-ok: <reason>'." >&2
    return 1
  fi
  echo "lint: ok (no blocking I/O on the engine dispatch hot path)"
}

lint
[ "${1:-}" = "--lint-only" ] && exit 0

# ROADMAP.md tier-1 verify command (kept in sync with the ROADMAP header).
set -o pipefail 2>/dev/null || true
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
  -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=$?
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit $rc

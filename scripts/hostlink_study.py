#!/usr/bin/env python
"""Measure the host→device link and derive reference-mode (Q5) CSV rows.

The reference's timing protocol re-distributes the operands every repetition
(quirk Q5, ``README.md:42-44``); on a tunneled TPU backend the literal
per-rep ``device_put`` protocol is the known wedge trigger (see
bench/hostlink.py). This script is the wedge-safe substitute:

1. measure the host→device link once over a bounded size ladder (no kills,
   no deletes racing transfers) and print the fitted latency/bandwidth model;
2. read amortized rows from the extended CSV;
3. derive and append reference-mode rows (``mode="reference_derived"``,
   ``measure="derived"``) to the per-strategy
   ``<strategy>_reference_derived.csv`` and the extended CSV — a separate
   file from literal ``mode="reference"`` measurements, so the two
   provenances never mix. Re-runs are idempotent per config.

Example::

    python scripts/hostlink_study.py --data-root data --max-mb 64
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-root", default="data", help="data directory root")
    p.add_argument(
        "--max-mb", type=int, default=256,
        help="largest transfer in the measurement ladder (MB)",
    )
    p.add_argument(
        "--reps", type=int, default=3, help="transfers per ladder size"
    )
    p.add_argument(
        "--platform", default=None,
        help="force a jax platform (config-level pin, like sweep.py)",
    )
    p.add_argument("--host-devices", type=int, default=None)
    args = p.parse_args(argv)

    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform

    configure_platform(args.platform, args.host_devices)

    from matvec_mpi_multiplier_tpu.bench.hostlink import (
        DEFAULT_LADDER_BYTES,
        derive_reference_result,
        measure_link,
    )
    from matvec_mpi_multiplier_tpu.bench.metrics import (
        append_result,
        extended_csv_path,
        read_csv,
    )
    from matvec_mpi_multiplier_tpu.bench.timing import TimingResult

    ladder = [b for b in DEFAULT_LADDER_BYTES if b <= args.max_mb * 2**20]
    link = measure_link(ladder, reps=args.reps)
    print(
        f"link: alpha={link.alpha_s * 1e3:.3f} ms  "
        f"bandwidth={link.gbps:.2f} GB/s  "
        f"({len(link.samples)} ladder points, min of {args.reps})"
    )

    ext = extended_csv_path(args.data_root)
    if not ext.exists():
        print(f"no amortized rows at {ext}; link model printed only")
        return 0

    def key(row):
        return (
            row["n_rows"], row["n_cols"], row["n_devices"], row["strategy"],
            row["dtype"], row.get("n_rhs", 1),
        )

    all_rows = read_csv(ext)
    # Idempotent re-runs: a config that already has a derived row keeps it
    # (appending a second would over-weight it in downstream averaging).
    already = {key(r) for r in all_rows if r.get("mode") == "reference_derived"}
    n_derived = n_skipped = 0
    for row in all_rows:
        if row.get("mode") != "amortized":
            continue
        if key(row) in already:
            n_skipped += 1
            continue
        already.add(key(row))
        amortized = TimingResult(
            n_rows=row["n_rows"],
            n_cols=row["n_cols"],
            n_devices=row["n_devices"],
            strategy=row["strategy"],
            dtype=row["dtype"],
            mode=row["mode"],
            measure=row["measure"],
            mean_time_s=row["time"],
            times_s=(row["time"],),
            n_rhs=row.get("n_rhs", 1),
        )
        derived = derive_reference_result(amortized, link)
        append_result(derived, args.data_root)
        n_derived += 1
    print(
        f"{n_derived} reference-mode rows derived"
        + (f", {n_skipped} already present (skipped)" if n_skipped else "")
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/bin/bash
# Kill and relaunch a WEDGED capture instead of waiting out its stage budget.
#
# The watcher (scripts/watch_and_capture.sh) already survives tunnel wedges:
# tpu_measure_all.py's per-stage timeout (90 min) kills a blocked stage and
# the watcher goes back to probing. But on days when healthy windows last
# ~12 minutes and wedges strike mid-stage, 90 minutes of waiting per wedge
# forfeits several windows. This nanny closes that gap with the one signal
# that separates a wedge from slow-but-healthy work: a wedged tunnel client
# blocks forever in C++ with ZERO host CPU advance, while every real stage
# (XLA compiles, jitter calibration, CSV flushes, figure rendering) burns
# host CPU at least every few minutes. block_until_ready waits are also
# near-zero-CPU, but no single on-device dispatch in any stage runs longer
# than ~1 min on this chip — far under the trip threshold.
#
# Mechanics: the watcher runs as the nanny's own child, and the monitored
# family is the watcher's /proc-walked descendant tree — never a global
# cmdline match, so hand-run studies or editors can neither be killed nor
# mask a wedge by burning CPU. The aggregate includes each process's
# reaped-children CPU (cutime/cstime), so a completed stage's ticks persist
# in the orchestrator's counters and the aggregate only ever grows while
# work is happening; a drop (pid set change mid-sample) resets the stall
# window rather than aging it. If the aggregate advances less than
# $MIN_TICKS over $STALL_S while a capture stage is up, the family is
# SIGKILLed (watcher first, so it cannot race a retry) and the watcher is
# relaunched; sweep stages resume over flushed rows (--skip-measured), so
# a kill costs at most the one in-flight config. Between captures (probe
# phase, no stage child alive) nothing is ever killed. When the watcher
# exits on its own, its real exit code (via wait) decides: rc 0 = capture
# complete, rc 1 = the watcher's own attempt budget ran out, rc 2 =
# deterministic failure — all three are voluntary and stop the nanny;
# anything else (OOM kill, stray signal) is involuntary and the watcher
# restarts.
#
# Usage: nohup bash scripts/capture_nanny.sh [watcher args...] > nanny.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
STALL_S="${NANNY_STALL_S:-600}"
POLL_S="${NANNY_POLL_S:-60}"
MIN_TICKS="${NANNY_MIN_TICKS:-200}"   # 2 s of CPU @ 100 Hz
MAX_RESTARTS="${NANNY_MAX_RESTARTS:-500}"
LOG="${NANNY_CAPTURE_LOG:-capture_r5.log}"

say() { echo "$(date -u +%FT%TZ) nanny: $*"; }

descendants() {  # pids of the tree rooted at $1 (including $1), via ppid walk
  local roots="$1" out="" pid ppid
  local -A child_of=()
  while read -r pid ppid; do
    child_of[$ppid]="${child_of[$ppid]:-} $pid"
  done < <(ps -e -o pid=,ppid=)
  while [ -n "$roots" ]; do
    set -- $roots; roots=""
    for pid in "$@"; do
      out="$out $pid"
      roots="$roots ${child_of[$pid]:-}"
    done
  done
  echo "$out"
}

ticks_of() {  # sum utime+stime+cutime+cstime over pids; vanished pids count 0
  local total=0 pid t
  for pid in "$@"; do
    if [ -r "/proc/$pid/stat" ]; then
      # fields 14-17; comm (field 2) may contain spaces, so cut from the
      # closing paren onward before counting fields
      t=$(awk '{n=index($0,")"); split(substr($0,n+2),f," ");
                print f[12]+f[13]+f[14]+f[15]}' "/proc/$pid/stat" 2>/dev/null) || t=0
      total=$((total + ${t:-0}))
    fi
  done
  echo "$total"
}

capture_up() {  # a capture (not just the probing watcher) is running?
  local pid
  for pid in "$@"; do
    if [ -r "/proc/$pid/cmdline" ] &&
       tr '\0' ' ' < "/proc/$pid/cmdline" 2>/dev/null |
         grep -q 'tpu_measure_all\.py'; then
      return 0
    fi
  done
  return 1
}

wpid=""
start_watcher() {
  bash scripts/watch_and_capture.sh "$@" >> "$LOG" 2>&1 &
  wpid=$!
  say "watcher started (pid $wpid)"
}

start_watcher "$@"

restarts=0
stall_ticks=-1   # aggregate at the start of the current stall window
stall_since=0
while :; do
  sleep "$POLL_S"
  if ! kill -0 "$wpid" 2>/dev/null; then
    wait "$wpid"; rc=$?
    if [ "$rc" -le 2 ]; then
      # All three voluntary watcher exits: 0 = capture complete, 1 = its
      # attempt budget ran out, 2 = deterministic capture failure.
      # Restarting on any of them would defeat the watcher's own policy.
      say "watcher exited rc=$rc (0=complete, 1=attempt budget, 2=deterministic failure) — nanny done"
      exit "$rc"
    fi
    say "watcher died involuntarily (rc=$rc) — restarting"
    restarts=$((restarts + 1))
    [ "$restarts" -ge "$MAX_RESTARTS" ] && { say "restart budget exhausted"; exit 1; }
    start_watcher "$@"
    stall_ticks=-1
    continue
  fi
  pids=$(descendants "$wpid")
  # shellcheck disable=SC2086
  if ! capture_up $pids; then
    stall_ticks=-1   # between captures (probe phase): reset the window
    continue
  fi
  # shellcheck disable=SC2086
  now_ticks=$(ticks_of $pids)
  now_s=$(date +%s)
  if [ "$stall_ticks" -lt 0 ] || [ "$now_ticks" -lt "$stall_ticks" ] ||
     [ $((now_ticks - stall_ticks)) -ge "$MIN_TICKS" ]; then
    stall_ticks="$now_ticks"
    stall_since="$now_s"
    continue
  fi
  if [ $((now_s - stall_since)) -lt "$STALL_S" ]; then
    continue
  fi
  restarts=$((restarts + 1))
  say "WEDGE: capture CPU advanced $((now_ticks - stall_ticks)) ticks in $((now_s - stall_since))s — killing family (restart $restarts/$MAX_RESTARTS)"
  kill -9 "$wpid" 2>/dev/null
  # shellcheck disable=SC2086
  kill -9 $pids 2>/dev/null
  wait "$wpid" 2>/dev/null
  sleep 2
  if [ "$restarts" -ge "$MAX_RESTARTS" ]; then
    say "restart budget exhausted — stopping"
    exit 1
  fi
  start_watcher "$@"
  stall_ticks=-1
done

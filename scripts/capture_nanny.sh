#!/bin/bash
# Kill and relaunch a WEDGED capture instead of waiting out its stage budget.
#
# The watcher (scripts/watch_and_capture.sh) already survives tunnel wedges:
# tpu_measure_all.py's per-stage timeout (90 min) kills a blocked stage and
# the watcher goes back to probing. But on days when healthy windows last
# ~12 minutes and wedges strike mid-stage, 90 minutes of waiting per wedge
# forfeits several windows. This nanny closes that gap with the one signal
# that separates a wedge from slow-but-healthy work: a wedged tunnel client
# blocks forever in C++ with ZERO host CPU advance, while every real stage
# (XLA compiles, jitter calibration, CSV flushes, figure rendering) burns
# host CPU at least every few minutes. block_until_ready waits are also
# near-zero-CPU, but no single on-device dispatch in any stage runs longer
# than ~1 min on this chip — far under the trip threshold.
#
# Mechanics: the watcher runs as the nanny's own child, and the monitored
# family is the watcher's /proc-walked descendant tree — never a global
# cmdline match, so hand-run studies or editors can neither be killed nor
# mask a wedge by burning CPU. The aggregate includes each process's
# reaped-children CPU (cutime/cstime), so a completed stage's ticks persist
# in the orchestrator's counters and the aggregate only ever grows while
# work is happening; a drop (pid set change mid-sample) resets the stall
# window rather than aging it. If the aggregate advances less than
# $MIN_TICKS over $STALL_S while a capture stage is up, the watcher's
# process group is SIGKILLed atomically and the watcher is
# relaunched; sweep stages resume over flushed rows (--skip-measured), so
# a kill costs at most the one in-flight config. Between captures (probe
# phase, no stage child alive) nothing is ever killed. When the watcher
# exits on its own, its real exit code (via wait) decides: rc 0 = capture
# complete, rc 1 = the watcher's own attempt budget ran out, rc 2 =
# deterministic failure — all three are voluntary and stop the nanny;
# anything else (OOM kill, stray signal) is involuntary and the watcher
# restarts.
#
# Usage: nohup bash scripts/capture_nanny.sh [watcher args...] > nanny.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
STALL_S="${NANNY_STALL_S:-600}"
POLL_S="${NANNY_POLL_S:-60}"
MIN_TICKS="${NANNY_MIN_TICKS:-200}"   # 2 s of CPU @ 100 Hz
MAX_RESTARTS="${NANNY_MAX_RESTARTS:-500}"
LOG="${NANNY_CAPTURE_LOG:-capture_r5.log}"

say() { echo "$(date -u +%FT%TZ) nanny: $*"; }

descendants() {  # pids of the tree rooted at $1 (including $1), via ppid walk
  local roots="$1" out="" pid ppid
  local -A child_of=()
  while read -r pid ppid; do
    child_of[$ppid]="${child_of[$ppid]:-} $pid"
  done < <(ps -e -o pid=,ppid=)
  while [ -n "$roots" ]; do
    set -- $roots; roots=""
    for pid in "$@"; do
      out="$out $pid"
      roots="$roots ${child_of[$pid]:-}"
    done
  done
  echo "$out"
}

ticks_of() {  # sum utime+stime+cutime+cstime over pids; vanished pids count 0
  local total=0 pid t
  for pid in "$@"; do
    if [ -r "/proc/$pid/stat" ]; then
      # fields 14-17; comm (field 2) may contain spaces or ')' itself, so
      # cut from the LAST closing paren onward before counting fields
      t=$(awk '{n=match($0, /\)[^)]*$/); split(substr($0,n+2),f," ");
                print f[12]+f[13]+f[14]+f[15]}' "/proc/$pid/stat" 2>/dev/null) || t=0
      total=$((total + ${t:-0}))
    fi
  done
  echo "$total"
}

capture_up() {  # a capture (not just the probing watcher) is running?
  local pid
  for pid in "$@"; do
    if [ -r "/proc/$pid/cmdline" ] &&
       tr '\0' ' ' < "/proc/$pid/cmdline" 2>/dev/null |
         grep -q 'tpu_measure_all\.py'; then
      return 0
    fi
  done
  return 1
}

wpid=""
start_watcher() {
  # Job control (set -m) gives the watcher its OWN process group with
  # pgid == $!: the family then stays findable by pgid even after the
  # leader dies (children reparent to init but keep the pgid), with no
  # pid snapshot to go stale between sample and kill.
  set -m
  bash scripts/watch_and_capture.sh "$@" >> "$LOG" 2>&1 &
  wpid=$!
  set +m
  say "watcher started (pid $wpid)"
}

group_members() {  # pids currently in the watcher's process group
  ps -e -o pid=,pgid= | awk -v g="$wpid" '$2 == g {print $1}'
}

family_pids() {  # group members + ALL their descendants: catches children
                 # that left the group or session (GNU timeout runs its
                 # command in its own group; jupyter kernels setsid) but
                 # still hang off a group member by ppid.
  local roots
  roots=$(group_members | tr '\n' ' ')
  case "$roots" in
    *[0-9]*) descendants "$roots" | tr ' ' '\n' | sort -un | tr '\n' ' ';;
    *) echo "";;
  esac
}

capture_cmdline() {  # 0 when $1's cmdline carries the capture fingerprint
  [ -r "/proc/$1/cmdline" ] &&
  tr '\0' ' ' < "/proc/$1/cmdline" 2>/dev/null |
    grep -Eq 'watch_and_capture|tpu_measure_all|bench\.sweep|_study\.py|autotune_pallas|derive_vmem_roof|stats_visualization|nbconvert|jupyter'
}

pid_in_group() {  # 0 when $1 still sits in the watcher's pgid right now
  [ "$(ps -o pgid= -p "$1" 2>/dev/null | tr -d ' ')" = "$wpid" ]
}

kill_family() {
  local fam pid matched=""
  fam=$(family_pids)
  case "$fam" in *[0-9]*) ;; *)
    say "no surviving processes in pgid $wpid — nothing to kill"
    return;;
  esac
  # Never strike a RECYCLED pgid: after the whole group is gone, $wpid can
  # be reassigned to an unrelated job within one poll interval. Require
  # the capture's own fingerprint among the members before killing.
  for pid in $fam; do
    if capture_cmdline "$pid"; then
      matched=1; break
    fi
  done
  if [ -z "$matched" ]; then
    say "pgid $wpid holds no capture-family cmdline (recycled pid?) — not killing"
    return
  fi
  kill -9 -- "-$wpid" 2>/dev/null
  # The group kill only reaches members still in the pgid; the per-pid
  # sweep exists for ESCAPEES (setsid'd jupyter kernels, GNU timeout's
  # own group). But $fam is a snapshot: between collecting it and
  # striking, an escapee may have exited and its pid been RECYCLED to an
  # unrelated process — the one-member fingerprint above says nothing
  # about the others. Re-verify EACH pid at strike time (still in the
  # verified group, or carrying the capture cmdline itself) and skip the
  # rest rather than kill on stale identity.
  for pid in $fam; do
    if pid_in_group "$pid" || capture_cmdline "$pid"; then
      kill -9 "$pid" 2>/dev/null
    else
      say "pid $pid no longer matches the capture family (exited or recycled) — skipping"
    fi
  done
}

start_watcher "$@"

restarts=0
stall_ticks=-1   # aggregate at the start of the current stall window
stall_since=0
while :; do
  sleep "$POLL_S"
  if ! kill -0 "$wpid" 2>/dev/null; then
    wait "$wpid"; rc=$?
    if [ "$rc" -le 2 ]; then
      # All three voluntary watcher exits: 0 = capture complete, 1 = its
      # attempt budget ran out, 2 = deterministic capture failure.
      # Restarting on any of them would defeat the watcher's own policy.
      say "watcher exited rc=$rc (0=complete, 1=attempt budget, 2=deterministic failure) — nanny done"
      exit "$rc"
    fi
    if [ "$rc" -eq 126 ] || [ "$rc" -eq 127 ]; then
      # Shell exec failures: 126 = watcher script not executable, 127 = not
      # found. Deterministic — relaunching the same command line MAX_RESTARTS
      # times (~8h of one-per-poll retries) cannot fix a missing/chmod-less
      # script, so treat as fatal instead of involuntary death.
      say "watcher launch failed rc=$rc (126=not executable, 127=not found) — deterministic exec failure, not retrying"
      exit "$rc"
    fi
    # The dead watcher's capture children reparent to init but keep its
    # pgid — group-kill them, or the relaunched watcher starts a SECOND
    # capture contending for the chip and the CSVs.
    kill_family
    say "watcher died involuntarily (rc=$rc) — killed orphans, restarting"
    restarts=$((restarts + 1))
    [ "$restarts" -ge "$MAX_RESTARTS" ] && { say "restart budget exhausted"; exit 1; }
    sleep 2   # let dying processes release the chip and close CSVs
    start_watcher "$@"
    stall_ticks=-1
    continue
  fi
  pids=$(descendants "$wpid")
  # shellcheck disable=SC2086
  if ! capture_up $pids; then
    stall_ticks=-1   # between captures (probe phase): reset the window
    continue
  fi
  # shellcheck disable=SC2086
  now_ticks=$(ticks_of $pids)
  now_s=$(date +%s)
  if [ "$stall_ticks" -lt 0 ] || [ "$now_ticks" -lt "$stall_ticks" ] ||
     [ $((now_ticks - stall_ticks)) -ge "$MIN_TICKS" ]; then
    stall_ticks="$now_ticks"
    stall_since="$now_s"
    continue
  fi
  if [ $((now_s - stall_since)) -lt "$STALL_S" ]; then
    continue
  fi
  restarts=$((restarts + 1))
  say "WEDGE: capture CPU advanced $((now_ticks - stall_ticks)) ticks in $((now_s - stall_since))s — killing family (restart $restarts/$MAX_RESTARTS)"
  kill_family
  wait "$wpid" 2>/dev/null
  sleep 2
  if [ "$restarts" -ge "$MAX_RESTARTS" ]; then
    say "restart budget exhausted — stopping"
    exit 1
  fi
  start_watcher "$@"
  stall_ticks=-1
done

#!/usr/bin/env python
"""One-shot TPU measurement capture: everything the round needs, in order.

The tunneled TPU in this environment wedges unpredictably (see bench.py's
probe guard), so when it IS healthy every pending measurement should be
captured in one pass, highest-leverage-first, each stage flushing its
results to disk before the next starts — a wedge mid-run then loses only
the stages after it, and the stages it can least afford to lose ran first.
Stages:

1. probe      — subprocess jax.devices() check (abort early if wedged);
2. headline   — bench.py's blockwise bf16 bandwidth (prints the JSON line);
3. baseline   — 65536^2 bf16 blockwise (BASELINE.json's north-star config;
                8.6 GB of operands, generated on device). Runs IMMEDIATELY
                after the headline: it is the single highest-leverage
                artifact, and a capture that wedges mid-sweep must not
                lose it again (that is how round 3's first attempt died);
4. sweep_square — the square fp32 sweep, median-of-5 device-looped
                slopes (--measure loop: the rep loop is a fori_loop on
                device with a jitter-calibrated spread, so per-dispatch
                tunnel overhead never touches the number), replacing the
                round-1 noise-dominated rows; then the derived sub-VMEM
                roof (wedge-safe, reads the CSVs just written);
5. gemm       — MXU-bound GEMM numbers (8192^2 bf16 xla + pallas tiers,
                plus the fp64-parity ozaki tier);
6. compensated— scripts/compensated_study.py on the chip (accuracy vs the
                fp64 oracle + bandwidth rows);
6b. crossover — scripts/crossover_study.py: the GEMV→GEMM roofline knee
                (n_rhs sweep at 8192, bf16 — where the HBM-bound regime
                hands over to the MXU-bound one);
7. autotune   — scripts/autotune_pallas.py (bm, bk) tile search at the
                headline size vs the committed defaults;
8. autotune_gemm — scripts/autotune_pallas_gemm.py (bm, bn, bk) search at
                8192^2 bf16, reported as MFU vs the 197 TFLOP/s MXU peak;
   (5-8 are cheap one-shot stages that each close an evidence gap on
   their own, so they run BEFORE the long asymmetric sweep: observed
   healthy windows can be minutes, and --skip-measured resume means the
   sweeps lose nothing by going later)
9. sweep_asymmetric — the asymmetric fp32 sweep + a re-derived roof;
10. hostlink  — link model + derived reference-mode rows (the wedge-safe
                Q5 substitute; never does per-rep transfers);
11. overlap   — scripts/overlap_study.py on the real backend (async
                collective-permute pair evidence; self-skips at p=1);
12. refine / attention / autotune_attention — solver-accuracy and
                long-context evidence on the chip, then the causal
                flash-tile autotune matching the attention workload;
13. figures   — regenerate figures/tpu with HBM-roofline and MFU columns;
14. notebook  — re-execute stats_visualization.ipynb in place so its
                committed outputs match the dataset the capture just wrote
                (wedge-safe: the notebook reads CSVs, never the chip).

Usage: python scripts/tpu_measure_all.py [--skip STAGE ...] [--data-root data]

Exit codes: 0 = every stage ok (soft sweep skips allowed); 1 = retryable
(probe failed, a stage hit the wedge timeout, a sweep completed with
transient config failures [sweep rc 5], or the baseline degraded to the
cpu fallback — the resume redoes only what failed); 4 = ran to
completion and the failures are deterministic-class (stage crashes,
usage errors — the watcher must NOT endlessly re-run the capture on
those).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def probe(timeout_s: float = 120.0) -> bool:
    r = subprocess.run(
        [sys.executable, "-c", "import jax; jax.devices()"],
        timeout=timeout_s, capture_output=True, text=True,
    )
    return r.returncode == 0


# Generous per-stage budget: long enough that a healthy stage never gets
# killed mid-transfer (the documented wedge trigger), short enough that a
# mid-run wedge (child blocks forever in C++) doesn't hang the capture —
# later stages would also wedge, so a timeout aborts the rest.
STAGE_TIMEOUT_S = 5400.0


class StageWedged(RuntimeError):
    pass


def _has_nbconvert() -> bool:
    """Separate hook so tests can pin the stage decision deterministically
    (the [analysis] extra owns nbconvert; [test]-only environments lack it)."""
    return importlib.util.find_spec("nbconvert") is not None


def _reference_out() -> Path | None:
    """The reference's committed data/out, or None off the capture host —
    a separate hook so tests pin both overlay branches deterministically."""
    ref = Path("/root/reference/data/out")
    return ref if ref.is_dir() else None


def run(cmd: list[str]) -> int:
    print("+", " ".join(cmd), flush=True)
    # Persistent XLA compilation cache shared across stages: a re-capture
    # after a mid-run wedge skips every already-compiled config's compile
    # round-trips (each one is tunnel exposure). Harmlessly ignored by
    # backends that don't support it.
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", str(REPO / ".jax_cache"))
    try:
        return subprocess.call(cmd, cwd=REPO, env=env, timeout=STAGE_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        raise StageWedged(
            f"stage exceeded {STAGE_TIMEOUT_S:.0f}s (tunnel wedged mid-run); "
            "aborting remaining stages — earlier stages already flushed"
        ) from None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-root", default="data")
    p.add_argument(
        "--skip", nargs="*", default=[],
        choices=["headline", "sweeps", "hostlink", "gemm", "overlap",
                 "compensated", "crossover", "refine", "attention",
                 "autotune", "autotune_gemm", "autotune_attention",
                 "baseline", "figures", "notebook"],
    )
    p.add_argument(
        "--wipe-stale-csvs", action="store_true",
        help="move any pre-existing data/out/*.csv aside (to *.csv.stale) "
        "before the sweeps stage, so the capture produces a fresh, "
        "internally consistent dataset instead of appending to rows "
        "measured under an older protocol",
    )
    args = p.parse_args(argv)
    py = sys.executable

    try:
        if not probe():
            print("probe FAILED (backend errored) — aborting", flush=True)
            return 1
    except subprocess.TimeoutExpired:
        print("probe TIMED OUT (tunnel wedged) — aborting", flush=True)
        return 1
    print("probe OK — capturing all stages", flush=True)

    # Per-stage (name, rc, soft, retryable) record. A sweep under
    # --keep-going exits 3 when it completed with only UNMEASURABLE
    # (TimingError) skips — noise floor, not backend fault; re-running the
    # capture over it would burn the healthy window for rows a retry
    # cannot improve. Only sweep stages get that dispensation, and the
    # code is 3 (not 2) so an argparse usage error — exit 2 by
    # convention — can never read as soft. Sweep exit 5 = completed with
    # transient config failures (crashes exit 1; the sweep reserves 5 for
    # exactly this) — the RETRYABLE class, as is a baseline stage that
    # degraded to the cpu fallback (rc 1 there means the tunnel wedged
    # between the probe and the stage, and the north star must never be
    # forfeited over a transient).
    statuses: list[tuple[str, int, bool, bool]] = []

    def step(stage: str, cmd: list[str], sweep_stage: bool = False) -> None:
        rc = run(cmd)
        statuses.append((stage, rc, sweep_stage and rc == 3,
                         sweep_stage and rc == 5))

    try:
        if "headline" not in args.skip:
            step("headline", [py, "bench.py"])
        if "baseline" not in args.skip:
            # North-star first (after the cheap headline): the one artifact
            # a mid-capture wedge must never cost again.
            rc_b = _baseline_stage(py)
            statuses.append(("baseline", rc_b, False, rc_b == 1))
        # --skip-measured: every sweep-family stage resumes over whatever
        # rows an earlier (wedge-killed) attempt already flushed — a
        # healthy window only ever pays for configs not yet measured.
        # Safe because each attempt runs the same protocol on the same
        # chip; --wipe-stale-csvs (dropped by the watcher after the first
        # started attempt) is what retires rows from OLDER protocols.
        sweep = [py, "-m", "matvec_mpi_multiplier_tpu.bench.sweep",
                 "--data-root", args.data_root, "--keep-going",
                 "--skip-measured"]
        def sweep_stage(kind: str) -> None:
            step(f"sweep_{kind}",
                 sweep + ["--strategy", "all",
                          "--sweep", kind,
                          "--dtype", "float32", "--measure", "loop",
                          "--chain-samples", "5", "--n-reps", "50"],
                 sweep_stage=True)

        def vmem_roof_stage(tag: str = "vmem_roof") -> None:
            # Wedge-safe (reads the CSVs just written): derive the
            # measurement-based sub-VMEM sanity ceiling so the data-quality
            # gate tightens from the flat pre-measurement bound the moment
            # loop rows exist (tests/test_data_quality.py reads the JSON).
            step(tag, [py, "scripts/derive_vmem_roof.py",
                       "--data-root", args.data_root])

        # Stage order is tuned for SHORT healthy windows (the observed
        # 2026-07-31 window lasted ~12 minutes): after the square sweep —
        # the core dataset deliverable — the cheap one-shot stages that
        # each close an evidence gap on their own (GEMM/MFU tiers,
        # fp64-parity tiers on the MXU, the two tile autotunes; ~45 min
        # total) run BEFORE the long asymmetric sweep (~2 h). Per-stage
        # flushing + --skip-measured resume make the order safe: a wedge
        # anywhere loses only the stages after it, and a sweep interrupted
        # mid-run continues from its first unmeasured config next window.
        # Each sweep kind gets its own invocation and stage budget: the
        # jitter-calibrated spreads make a combined square+asymmetric run
        # (~114 configs incl. compiles) brush the per-stage timeout, and a
        # timeout would abort every later stage.
        if "sweeps" not in args.skip:
            if args.wipe_stale_csvs:
                _wipe_stale_csvs(Path(args.data_root) / "out")
            sweep_stage("square")
            vmem_roof_stage()
        if "gemm" not in args.skip:
            step("gemm_xla",
                 sweep + ["--op", "gemm", "--strategy", "all",
                          "--sizes", "8192", "--dtype", "bfloat16",
                          "--measure", "loop", "--n-reps", "20"],
                 sweep_stage=True)
            step("gemm_pallas",
                 sweep + ["--op", "gemm", "--strategy", "blockwise",
                          "--sizes", "8192", "--dtype", "bfloat16",
                          "--kernel", "pallas", "--measure", "loop",
                          "--n-reps", "20",
                          # Own label: unlabeled pallas rows would be
                          # averaged with the xla rows at the same key.
                          "--label-suffix", "pallas"],
                 sweep_stage=True)
            # fp64-parity GEMM on the int8 MXU (ops/ozaki_gemm.py): the
            # accuracy story is pinned by tests; this lands its measured
            # on-chip cost next to the xla/pallas tiers.
            step("gemm_ozaki",
                 sweep + ["--op", "gemm", "--strategy", "blockwise",
                          "--sizes", "8192", "--dtype", "float32",
                          "--kernel", "ozaki", "--measure", "loop",
                          "--n-reps", "10",
                          "--label-suffix", "ozaki"],
                 sweep_stage=True)
        if "compensated" not in args.skip:
            # fp64-parity evidence on the chip: accuracy vs the fp64 oracle
            # + bandwidth rows (docs/COMPENSATED.md, backend=tpu).
            step("compensated",
                 [py, "scripts/compensated_study.py", "--size", "8192",
                  "--data-root", args.data_root])
        if "crossover" not in args.skip:
            # The roofline-knee study: same blockwise engine, n_rhs swept
            # from the reference's r=1 regime into MXU saturation.
            step("crossover",
                 [py, "scripts/crossover_study.py",
                  "--data-root", args.data_root])
        if "autotune" not in args.skip:
            # Pallas tile search at the headline size: if a tile beats the
            # committed (512, 4096) defaults the report says which.
            step("autotune", [py, "scripts/autotune_pallas.py"])
        if "autotune_gemm" not in args.skip:
            # MXU tile search: the MFU face of the autotune story.
            step("autotune_gemm", [py, "scripts/autotune_pallas_gemm.py"])
        if "sweeps" not in args.skip:
            sweep_stage("asymmetric")
            # Re-derive the sub-VMEM ceiling over the full dataset: the
            # asymmetric regime's small operands are sub-VMEM too and may
            # move the fastest-row basis.
            vmem_roof_stage("vmem_roof_asym")
        if "hostlink" not in args.skip:
            step("hostlink", [py, "scripts/hostlink_study.py",
                              "--data-root", args.data_root, "--max-mb", "256"])
        if "overlap" not in args.skip:
            # Real-backend overlap evidence: async collective-permute
            # start/done pairs in the compiled module + TPU timings
            # (docs/OVERLAP.md regenerated with backend=tpu).
            step("overlap", [py, "scripts/overlap_study.py", "--size", "8192"])
        if "refine" not in args.skip:
            # Solver-level accuracy evidence on the chip: iterative
            # refinement's forward-error ladder (docs/REFINEMENT.md,
            # backend=tpu) — the accuracy tiers working inside a solver.
            step("refine", [py, "scripts/refine_study.py", "--size", "2048"])
        if "attention" not in args.skip:
            # Long-context evidence on the chip: ring vs Ulysses (xla AND
            # fused-pallas tiers) vs the replicated dense baseline
            # (docs/ATTENTION.md, backend=tpu). Single chip: schedules
            # collapse to p=1. The dense oracle check and the xla tiers
            # materialize the (h, s, s) scores — 8192 tops out around
            # 2.1 GB fp32 per buffer, safely inside HBM; 16384 would be
            # 8.6 GB per intermediate and OOM those variants (the flash
            # tiers alone would fit, but the stage times all of them).
            step("attention", [py, "scripts/attention_study.py",
                               "--seqs", "4096", "8192", "--causal"])
        if "autotune_attention" not in args.skip:
            # Flash-attention tile search: the fused tier's (bq, bk) grid
            # vs the score-materializing xla tier at the p=1 shape AND
            # masking the attention stage measures (--causal, matching the
            # attention step above — causal masking shifts the tile's
            # MXU/VPU balance, so tuning non-causal could crown the wrong
            # winner). docs/AUTOTUNE_ATTENTION.md.
            step("autotune_attention",
                 [py, "scripts/autotune_pallas_attention.py", "--causal"])
        if "figures" not in args.skip:
            # --overlay puts this framework's TPU curves directly over the
            # reference's committed MPI curves in one figure (VERDICT
            # round-4 item 5: amortized vs derived-reference vs reference
            # at the largest shared size). Guarded: on a host without the
            # reference mount the stage still produces every per-strategy
            # and roofline figure instead of dying in the overlay loop.
            fig_cmd = [py, "scripts/stats_visualization.py",
                       "--data-out", str(Path(args.data_root) / "out"),
                       "--fig-dir", "figures/tpu", "--itemsize", "4",
                       "--hbm-peak", "819", "--mxu-peak", "197"]
            ref_out = _reference_out()
            if ref_out is not None:
                fig_cmd += ["--overlay", f"reference={ref_out}",
                            f"tpu={Path(args.data_root) / 'out'}"]
            step("figures", fig_cmd)
        if "notebook" not in args.skip:
            # Committed notebook outputs must match the dataset just written
            # (the reference's C13 role). Wedge-safe: reads CSVs only.
            # The notebook reads the committed data/out; re-executing it
            # against a custom --data-root would refresh its outputs over a
            # dataset it did not read, so the stage only runs for the
            # default root. nbconvert is a viz-only dependency ([analysis]
            # extra) — its absence must not flip a measurement capture's rc.
            if args.data_root != "data":
                print("notebook stage skipped: non-default --data-root "
                      "(the notebook reads the committed data/out)",
                      flush=True)
            elif not _has_nbconvert():
                print("notebook stage skipped: nbconvert not installed "
                      "(pip install '.[analysis]')", flush=True)
            else:
                step("notebook",
                     [py, "-m", "jupyter", "nbconvert", "--to",
                      "notebook", "--execute", "--inplace",
                      "--ExecutePreprocessor.timeout=600",
                      "stats_visualization.ipynb"])
    except StageWedged as e:
        print(f"ABORT: {e}", flush=True)
        return 1
    hard = [s for s, rc, soft, retry in statuses
            if rc != 0 and not soft and not retry]
    retryable = [s for s, _, _, retry in statuses if retry]
    for stage, rc, soft, retry in statuses:
        tag = ("ok" if rc == 0
               else "soft-skip" if soft
               else "RETRY" if retry
               else "FAILED")
        print(f"stage {stage}: rc={rc} {tag}", flush=True)
    print(f"capture complete — {len(hard)} hard-failed stage(s)"
          + (f": {', '.join(hard)}" if hard else "")
          + (f"; {len(retryable)} retryable: {', '.join(retryable)}"
             if retryable else ""), flush=True)
    # rc separates RETRYABLE aborts from COMPLETED runs so the watcher can
    # tell them apart: 1 = retryable (probe failure / wedge timeout / a
    # sweep that completed with transient config failures / the baseline
    # degrading to the cpu fallback — and --skip-measured makes a sweep
    # retry redo only the failures), 4 = every stage ran to completion and
    # the failures are deterministic-class (stage crashes, usage errors) —
    # an unlimited-retry watcher re-running the whole capture on those
    # would burn the healthy window in a loop. A retryable failure
    # outranks a coexisting deterministic one: the retry re-fails the
    # deterministic stage cheaply, and once the retryable stages complete
    # the deterministic failure alone yields 4 and stops the loop.
    if retryable:
        print(f"retryable stage failure(s): {', '.join(retryable)} — "
              "exiting 1 so the watcher tries again at the next healthy "
              "window (sweep retries redo only the failed configs)",
              flush=True)
        return 1
    return 4 if hard else 0


def _wipe_stale_csvs(out_dir: Path) -> None:
    """Move pre-existing top-level CSVs aside (never touches cpu_mesh/).

    Once per round: the first wipe writes a ``.stale_wiped`` sentinel and
    later runs return without touching anything — a watcher retry after a
    mid-capture wedge must resume over the rows the earlier attempt
    flushed (sweep stages pass ``--skip-measured``), not set its own
    round's partial dataset aside. ``land_capture.py --apply`` clears the
    sentinel when the round's dataset lands, re-arming the wipe for the
    next round's protocol.

    Backups are never overwritten: a second capture run must not clobber the
    first run's set-aside data with its own (possibly wedge-truncated) CSVs.
    """
    sentinel = out_dir / ".stale_wiped"
    if sentinel.exists():
        print(f"stale-CSV wipe already done this round ({sentinel} exists) "
              "— resuming over the current dataset", flush=True)
        return
    for csv in sorted(out_dir.glob("*.csv")):
        stale = csv.with_suffix(".csv.stale")
        n = 2
        while stale.exists():
            stale = csv.with_suffix(f".csv.stale{n}")
            n += 1
        print(f"moving stale {csv} -> {stale}", flush=True)
        csv.replace(stale)
    out_dir.mkdir(parents=True, exist_ok=True)
    sentinel.write_text(
        "stale CSVs wiped this round; land_capture.py --apply removes this "
        "sentinel\n"
    )


def _baseline_stage(py: str) -> int:
    env = dict(os.environ, MATVEC_BENCH_SIZE="65536")
    env.setdefault("JAX_COMPILATION_CACHE_DIR", str(REPO / ".jax_cache"))
    print("+ MATVEC_BENCH_SIZE=65536 bench.py", flush=True)
    try:
        r = subprocess.run(
            [py, "bench.py"], cwd=REPO, env=env, capture_output=True,
            text=True, timeout=STAGE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        raise StageWedged("baseline bench exceeded the stage budget") from None
    print(r.stdout.strip(), flush=True)
    line = (r.stdout.strip().splitlines() or [""])[-1]
    try:
        payload = json.loads(line)
    except json.JSONDecodeError:
        print("baseline stage produced no JSON line", flush=True)
        return 1
    if payload.get("backend") == "cpu-fallback":
        # bench.py degraded (tunnel wedged between our probe and this
        # stage): a CPU number must never be written as the 65536^2 bf16
        # north-star artifact.
        print("baseline stage got the CPU fallback — not writing the "
              "baseline artifact", flush=True)
        return 1
    out = REPO / "BASELINE_65536_bf16.json"
    out.write_text(json.dumps(payload, indent=2) + "\n")
    print(f"wrote {out}", flush=True)
    return r.returncode


if __name__ == "__main__":
    sys.exit(main())

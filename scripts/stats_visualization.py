#!/usr/bin/env python
"""Analysis CLI: compute SpeedUp/Efficiency tables and emit figures.

Reinstates the reference's missing ``stats_visualization.ipynb`` (C13,
``.MISSING_LARGE_BLOBS:1``) as a script. Reads reference-schema CSVs from a
``data/out`` directory (this framework's output or the reference's own
committed CSVs) and writes:

* a markdown scaling table per strategy (stdout),
* per-strategy Time/SpeedUp/Efficiency figures,
* a cross-strategy comparison figure at the largest common size.

Example::

    python scripts/stats_visualization.py --data-out /root/reference/data/out \
        --fig-dir figures/reference
    python scripts/stats_visualization.py --data-out data/out --itemsize 4
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from matvec_mpi_multiplier_tpu.analysis.plots import (
    plot_comparison,
    plot_overlay,
    plot_roofline,
    plot_strategy,
)
from matvec_mpi_multiplier_tpu.analysis.stats import format_table, load_strategy_csv


_ITEMSIZE = {"float64": 8, "float32": 4, "bfloat16": 2, "float16": 2}


def _ext_lookups(
    data_out: Path,
) -> tuple[
    dict[str, dict[tuple[int, int, int], int]],
    dict[str, dict[tuple[int, int, int], int]],
]:
    """Per-strategy (m, n, p) → n_rhs and → itemsize maps from the extended
    CSV.

    The reference CSV schema cannot carry the GEMM RHS width or the operand
    dtype; without the lookups, GEMM GFLOP/s would be understated by a
    factor of n_rhs, and a mixed-dtype dataset (fp32 matvec sweeps + bf16
    GEMM rows) would have GB/s misstated for whichever rows the single
    global --itemsize doesn't match."""
    from matvec_mpi_multiplier_tpu.bench.metrics import read_csv

    ext = data_out / "results_extended.csv"
    n_rhs_l: dict[str, dict[tuple[int, int, int], int]] = {}
    item_l: dict[str, dict[tuple[int, int, int], int]] = {}
    if ext.exists():
        for r in read_csv(ext):
            key = (r["n_rows"], r["n_cols"], r["n_devices"])
            n_rhs = r.get("n_rhs", 1)
            if isinstance(n_rhs, int) and n_rhs > 1:
                n_rhs_l.setdefault(r["strategy"], {})[key] = n_rhs
            isz = _ITEMSIZE.get(str(r.get("dtype", "")))
            if isz is not None:
                per = item_l.setdefault(r["strategy"], {})
                # Same (size, p) swept at two dtypes: the averaged row has
                # no single true itemsize — mark ambiguous (None) so the
                # table falls back to the explicit --itemsize rather than
                # silently taking whichever row came last.
                per[key] = isz if per.get(key, isz) == isz else None
    for per in item_l.values():
        for key in [k for k, v in per.items() if v is None]:
            del per[key]
    return n_rhs_l, item_l


def load_run(data_out: Path) -> dict[str, list]:
    """Load every per-strategy CSV in a data/out directory, keyed by stem
    (the one place the stem convention / results_extended exclusion lives)."""
    lookups, item_lookups = _ext_lookups(data_out)

    def strategy_of(stem: str) -> str:
        # Strip the sweep-variant prefix and timing-mode suffixes so every
        # file variant of a strategy (asymmetric_, _reference,
        # _reference_derived) hits the same extended-CSV strategy key.
        stem = stem.replace("asymmetric_", "")
        for suffix in ("_reference_derived", "_reference"):
            stem = stem.removesuffix(suffix)
        return stem

    run: dict[str, list] = {}
    for path in sorted(data_out.glob("*.csv")):
        if path.stem == "results_extended":
            continue
        run.setdefault(path.stem, []).extend(
            load_strategy_csv(
                path,
                n_rhs_lookup=lookups.get(strategy_of(path.stem)),
                itemsize_lookup=item_lookups.get(strategy_of(path.stem)),
            )
        )
    return run


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-out", default="data/out", help="directory of CSVs")
    p.add_argument("--fig-dir", default="figures", help="output directory")
    p.add_argument(
        "--itemsize", type=int, default=8,
        help="bytes per element for GB/s (8=fp64, 4=fp32, 2=bf16)",
    )
    p.add_argument(
        "--hbm-peak", type=float, default=None, metavar="GBPS",
        help="per-chip HBM peak GB/s; adds the roofline %%-of-peak column "
        "(BASELINE.json north star), e.g. 819 for TPU v5e",
    )
    p.add_argument(
        "--mxu-peak", type=float, default=None, metavar="TFLOPS",
        help="per-chip MXU peak TFLOP/s; adds the MFU (%%-of-MXU-peak) "
        "column — the compute roofline for GEMM rows, e.g. 197 bf16 "
        "TFLOP/s for TPU v5e",
    )
    p.add_argument(
        "--overlay", nargs="+", default=None, metavar="LABEL=DIR",
        help="overlay runs from multiple data/out dirs in one figure at the "
        "largest shared size, e.g. --overlay 'reference=/root/reference/"
        "data/out' 'this work=data/out/cpu_mesh' (BASELINE.json: TPU curves "
        "directly over the reference's MPI curves)",
    )
    args = p.parse_args(argv)
    if args.hbm_peak is not None and args.hbm_peak <= 0:
        p.error("--hbm-peak must be positive")
    if args.mxu_peak is not None and args.mxu_peak <= 0:
        p.error("--mxu-peak must be positive")

    data_out = Path(args.data_out)
    by_strategy = load_run(data_out)
    if not by_strategy and not args.overlay:
        print(f"no CSVs in {data_out}", file=sys.stderr)
        return 1

    for name, points in by_strategy.items():
        print(f"\n## {name}\n")
        print(
            format_table(
                points, itemsize=args.itemsize, hbm_peak_gbps=args.hbm_peak,
                mxu_peak_tflops=args.mxu_peak,
            )
        )
        fig = plot_strategy(points, Path(args.fig_dir) / f"{name}.png",
                            title=name)
        print(f"\nfigure: {fig}")

    if args.hbm_peak is not None and by_strategy:
        # Memory-side roofline: matvec bandwidth vs per-chip operand bytes
        # against the HBM peak, with the VMEM-residency boundary drawn.
        # One figure per device count PRESENT in the dataset (the roof and
        # per-chip bytes both scale with p; a hard-coded p=1 would silently
        # drop every multi-device row from the figure).
        matvec = {
            k: v for k, v in by_strategy.items() if not k.startswith("gemm")
        }
        counts = sorted({
            q.n_processes for pts in matvec.values() for q in pts
            if q.n_rhs == 1
        })
        for n_proc in counts:
            suffix = "" if n_proc == 1 else f"_p{n_proc}"
            fig = plot_roofline(
                matvec,
                Path(args.fig_dir) / f"roofline{suffix}.png",
                itemsize=args.itemsize, hbm_peak_gbps=args.hbm_peak,
                n_processes=n_proc,
            )
            if fig is not None:
                print(f"\nroofline figure (p={n_proc}): {fig}")

    if args.overlay:
        runs: dict[str, dict[str, list]] = {}
        for spec in args.overlay:
            label, _, d = spec.partition("=")
            if not d:
                p.error(f"--overlay expects LABEL=DIR, got {spec!r}")
            run = load_run(Path(d))
            if not run:
                p.error(f"--overlay: no strategy CSVs in {d!r}")
            runs[label] = run
        # Largest size present in every run.
        size_sets = [
            {(q.n_rows, q.n_cols) for pts in run.values() for q in pts}
            for run in runs.values()
        ]
        shared_sizes = set.intersection(*size_sets) if size_sets else set()
        if shared_sizes:
            m, n = max(shared_sizes, key=lambda s: s[0] * s[1])
            fig = plot_overlay(
                runs, m, n, Path(args.fig_dir) / f"overlay_{m}x{n}.png"
            )
            print(f"\noverlay figure: {fig}")
        else:
            print("\nno size shared by all overlay runs", file=sys.stderr)

    # Comparison at the largest size shared by >1 strategy — per op:
    # matvec and GEMM curves never share a figure (different operations,
    # different FLOP counts; a mixed plot would invite a false comparison).
    for op, strategies in (
        ("comparison", {k: v for k, v in by_strategy.items()
                        if not k.startswith("gemm")}),
        ("gemm_comparison", {k: v for k, v in by_strategy.items()
                             if k.startswith("gemm")}),
    ):
        sizes: dict[tuple[int, int], int] = {}
        for points in strategies.values():
            for size in {(q.n_rows, q.n_cols) for q in points}:
                sizes[size] = sizes.get(size, 0) + 1
        shared = [s for s, c in sizes.items() if c > 1]
        if shared:
            m, n = max(shared, key=lambda s: s[0] * s[1])
            fig = plot_comparison(
                strategies, m, n, Path(args.fig_dir) / f"{op}_{m}x{n}.png"
            )
            print(f"\n{op} figure: {fig}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Analysis CLI: compute SpeedUp/Efficiency tables and emit figures.

Reinstates the reference's missing ``stats_visualization.ipynb`` (C13,
``.MISSING_LARGE_BLOBS:1``) as a script. Reads reference-schema CSVs from a
``data/out`` directory (this framework's output or the reference's own
committed CSVs) and writes:

* a markdown scaling table per strategy (stdout),
* per-strategy Time/SpeedUp/Efficiency figures,
* a cross-strategy comparison figure at the largest common size.

Example::

    python scripts/stats_visualization.py --data-out /root/reference/data/out \
        --fig-dir figures/reference
    python scripts/stats_visualization.py --data-out data/out --itemsize 4
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from matvec_mpi_multiplier_tpu.analysis.plots import plot_comparison, plot_strategy
from matvec_mpi_multiplier_tpu.analysis.stats import format_table, load_strategy_csv


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-out", default="data/out", help="directory of CSVs")
    p.add_argument("--fig-dir", default="figures", help="output directory")
    p.add_argument(
        "--itemsize", type=int, default=8,
        help="bytes per element for GB/s (8=fp64, 4=fp32, 2=bf16)",
    )
    p.add_argument(
        "--hbm-peak", type=float, default=None, metavar="GBPS",
        help="per-chip HBM peak GB/s; adds the roofline %%-of-peak column "
        "(BASELINE.json north star), e.g. 819 for TPU v5e",
    )
    args = p.parse_args(argv)
    if args.hbm_peak is not None and args.hbm_peak <= 0:
        p.error("--hbm-peak must be positive")

    data_out = Path(args.data_out)
    csvs = sorted(data_out.glob("*.csv"))
    if not csvs:
        print(f"no CSVs in {data_out}", file=sys.stderr)
        return 1

    by_strategy: dict[str, list] = {}
    for path in csvs:
        if path.stem == "results_extended":
            continue
        points = load_strategy_csv(path)
        by_strategy.setdefault(path.stem, []).extend(points)
        print(f"\n## {path.stem}\n")
        print(
            format_table(
                points, itemsize=args.itemsize, hbm_peak_gbps=args.hbm_peak
            )
        )
        fig = plot_strategy(points, Path(args.fig_dir) / f"{path.stem}.png",
                            title=path.stem)
        print(f"\nfigure: {fig}")

    # Comparison at the largest size shared by >1 strategy.
    sizes: dict[tuple[int, int], int] = {}
    for points in by_strategy.values():
        for size in {(q.n_rows, q.n_cols) for q in points}:
            sizes[size] = sizes.get(size, 0) + 1
    shared = [s for s, c in sizes.items() if c > 1]
    if shared:
        m, n = max(shared, key=lambda s: s[0] * s[1])
        fig = plot_comparison(
            by_strategy, m, n, Path(args.fig_dir) / f"comparison_{m}x{n}.png"
        )
        print(f"\ncomparison figure: {fig}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

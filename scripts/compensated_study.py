#!/usr/bin/env python
"""Compensated-kernel evidence: accuracy vs the fp64 oracle + bandwidth row.

Round-2 review finding: ``ops/compensated.py`` claims fp64-grade accumulation
for fp32 data (the reference computes in C ``double``,
``src/matr_utils.c:86-96``), but the claim had only CPU property tests — no
committed accuracy-vs-fp64 comparison and no bandwidth row. This study
produces both, on whatever backend is active:

* **Accuracy** — a cancellation-heavy GEMV (rows of large-magnitude pairs
  summing to O(1) values: the case where naive fp32 loses all significant
  bits) evaluated by the ``xla`` fp32 kernel, the ``compensated`` kernel, and
  a numpy fp64 oracle; reports max relative error and max error in fp32 ulps
  of the oracle value for both.
* **Bandwidth** — the benchmark protocol at a real size with
  ``kernel=compensated`` vs ``kernel=xla``, appended to the extended CSV via
  the normal metrics path (``--data-root``; ``--no-csv`` to skip).

Writes/updates a markdown report (default ``docs/COMPENSATED.md``).

Usage::

    python scripts/compensated_study.py --platform cpu --host-devices 8
    python scripts/compensated_study.py --size 8192      # real backend (TPU)
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# First entry is the speed baseline the slowdown column is measured against.
KERNELS = ("xla", "compensated", "ozaki", "ozaki6", "ozaki_i8")


def cancellation_case(n_rows: int, n_cols: int, rng) -> tuple:
    """A matrix whose every row pairs +v with -v for large v, plus a small
    O(1) residual — the dot product's true value is the residual sum, but
    naive fp32 accumulation destroys it (catastrophic cancellation)."""
    import numpy as np

    big = rng.uniform(1e6, 1e7, size=(n_rows, n_cols // 2)).astype(np.float32)
    small = rng.uniform(-1.0, 1.0, size=(n_rows, n_cols // 2)).astype(np.float32)
    # Columns interleaved so the cancellation is spread across the row.
    a = np.empty((n_rows, n_cols), np.float32)
    a[:, 0::2] = big + small
    a[:, 1::2] = -big
    x = np.ones(n_cols, np.float32)
    return a, x


def ulp_error(y, oracle) -> float:
    """Max |y - oracle| measured in fp32 ulps of the oracle value."""
    import numpy as np

    oracle32 = oracle.astype(np.float32).astype(np.float64)
    ulp = np.spacing(np.abs(oracle32).astype(np.float32)).astype(np.float64)
    return float(np.max(np.abs(y.astype(np.float64) - oracle) / ulp))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--platform", default=None)
    p.add_argument("--host-devices", type=int, default=None)
    p.add_argument("--size", type=int, default=4096)
    p.add_argument("--acc-rows", type=int, default=512)
    p.add_argument("--acc-cols", type=int, default=4096)
    p.add_argument("--n-reps", type=int, default=25)
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--data-root", default=None)
    p.add_argument("--no-csv", action="store_true")
    p.add_argument("--report", default=str(REPO / "docs" / "COMPENSATED.md"))
    p.add_argument("--no-report", action="store_true")
    args = p.parse_args(argv)
    if args.acc_cols % 2:
        p.error("--acc-cols must be even (cancellation pairs are interleaved)")

    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform

    configure_platform(args.platform, args.host_devices)

    import jax
    import numpy as np

    from matvec_mpi_multiplier_tpu.bench.metrics import append_result
    from matvec_mpi_multiplier_tpu.bench.timing import benchmark_strategy
    from matvec_mpi_multiplier_tpu.models import get_strategy
    from matvec_mpi_multiplier_tpu.utils.errors import TimingError
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh

    platform = jax.devices()[0].platform
    n_dev = args.devices or len(jax.devices())
    mesh = make_mesh(n_dev)
    rng = np.random.default_rng(11)

    # -- Accuracy on the cancellation-heavy case ---------------------------
    a, x = cancellation_case(args.acc_rows, args.acc_cols, rng)
    oracle = a.astype(np.float64) @ x.astype(np.float64)
    strat = get_strategy("rowwise")
    # Fail with the typed ShardingError (not a deep XLA partitioning error)
    # when acc-rows doesn't divide the mesh, as every other entry point does.
    strat.validate(a.shape[0], a.shape[1], mesh)
    results = {}
    for kernel in KERNELS:
        fn = strat.build(mesh, kernel=kernel)
        y = np.asarray(fn(a, x))
        rel = float(np.max(np.abs(y.astype(np.float64) - oracle)
                           / np.maximum(np.abs(oracle), 1e-300)))
        results[kernel] = {"rel": rel, "ulp": ulp_error(y, oracle)}
        print(f"accuracy[{kernel}]: max rel err {rel:.3e}, "
              f"max ulp err {results[kernel]['ulp']:.3g}")

    # -- Bandwidth at a real size -----------------------------------------
    n = args.size
    ab = rng.standard_normal((n, n)).astype(np.float32)
    xb = rng.standard_normal(n).astype(np.float32)
    bw = {}
    for kernel in KERNELS:
        # Retry once, then degrade: a noisy tunnel window must not discard
        # the accuracy evidence already computed above — the report is
        # written either way, with the bandwidth cell marked unmeasurable.
        res = None
        for attempt in (1, 2):
            try:
                res = benchmark_strategy(
                    strat, mesh, ab, xb, n_reps=args.n_reps, kernel=kernel,
                )
                break
            except TimingError as e:
                print(f"bandwidth[{kernel}] attempt {attempt}: "
                      f"UNMEASURABLE ({e})", file=sys.stderr)
        bw[kernel] = res
        if res is None:
            continue
        if not args.no_csv:
            # Relabel BOTH rows with the kernel so neither lands in the
            # sweep's plain rowwise.csv (the reference schema carries no
            # kernel column; a stray off-grid row would contaminate the
            # SpeedUp/Efficiency averaging, see bench/metrics.py).
            import dataclasses

            append_result(
                dataclasses.replace(res, strategy=f"rowwise_{kernel}"),
                args.data_root,
            )
        print(f"bandwidth[{kernel}]: {res.mean_time_s*1e3:.3f} ms, "
              f"{res.gbps:.2f} GB/s")

    slowdowns = {
        kernel: (bw[kernel].mean_time_s / bw["xla"].mean_time_s
                 if bw["xla"] is not None and bw[kernel] is not None else None)
        for kernel in KERNELS[1:]
    }
    measure_label = bw["xla"].measure if bw["xla"] is not None else "loop"
    report = [
        "# Compensated (double-float) kernel: measured evidence",
        "",
        f"Backend: **{platform}**, {n_dev}-device mesh; accuracy case "
        f"{args.acc_rows}×{args.acc_cols} fp32 with interleaved ±10⁶..10⁷ "
        "cancellation pairs (true row sums are O(1)); bandwidth at "
        f"{n}² fp32, measure={measure_label}, {args.n_reps} reps "
        "(generated by `scripts/compensated_study.py`).",
        "",
        "| kernel | max rel err vs fp64 oracle | max err (fp32 ulps of "
        "oracle) | time (ms) | effective GB/s |",
        "|---|---|---|---|---|",
    ]
    for kernel in KERNELS:
        r, b = results[kernel], bw[kernel]
        timing_cells = (
            f"{b.mean_time_s*1e3:.3f} | {b.gbps:.2f}"
            if b is not None else "unmeasurable | —"
        )
        report.append(
            f"| {kernel} | {r['rel']:.3e} | {r['ulp']:.3g} | {timing_cells} |"
        )
    report += [""] + [
        (f"{kernel}/xla slowdown at {n}²: **{sd:.1f}×**."
         if sd is not None else
         f"{kernel}/xla slowdown at {n}²: unmeasurable this window.")
        for kernel, sd in slowdowns.items()
    ] + [
        "",
        "The cancellation case is the reference-parity stress test: the "
        "reference accumulates in C `double` where this case is exact to "
        "~1e-16; naive fp32 accumulation loses every significant bit "
        "(rel err ≥ 1). `kernel=compensated` (`ops/compensated.py`, "
        "error-free transformations + double-float tree reduction) must "
        "recover the oracle to within a few fp32 ulps — fp64-grade "
        "accuracy from fp32 hardware, at the measured bandwidth cost above. "
        "`kernel=ozaki` (`ops/ozaki.py`) reaches the same accuracy class "
        "by slicing operands into 8-bit-aligned bf16 addends whose block "
        "dots are exact in fp32 — the bulk arithmetic becomes one batched "
        "MXU contraction instead of per-element VPU transformations, "
        "closing most of the compensated tier's speed gap (`ozaki6` widens "
        "the per-block accuracy window from 32 to 48 bits). "
        "`kernel=ozaki_i8` (`ops/ozaki_gemm.py`) is the int8 "
        "formulation of the same idea — 7-bit slices, exact int32 "
        "contraction through k=2^16 per dot, the natural form for "
        "the MXU's integer mode and the registry's rank-2 GEMM "
        "tier, registered for GEMV so both formulations are "
        "measured side by side.",
    ]
    text = "\n".join(report) + "\n"
    print("\n" + text)
    if not args.no_report:
        out = Path(args.report)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(text)
        print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Autotune the Pallas flash-attention tile (bq, bk) on the real chip.

Third face of the autotune family (GEMV tiles: HBM-bound; GEMM tiles:
MXU-bound): the fused attention tile (``ops/pallas_attention.py``) sits
between — an MXU contraction pair around a VPU softmax, where the (bq, bk)
score-tile shape sets the MXU/VPU interleave and the VMEM working set.
Sweeps a (bq, bk) grid at the p=1 full-attention shape (the single-chip
case the capture's attention stage measures), times each tile against the
score-materializing XLA tier, and reports the table + winner
(docs/AUTOTUNE_ATTENTION.md). Tile configs that fail to compile are
recorded and skipped.

TPU-only by default: off-TPU pallas runs in interpret mode (pass
--allow-interpret --platform cpu --size 256 to smoke-test the plumbing).

Usage::

    python scripts/autotune_pallas_attention.py            # on the chip
    python scripts/autotune_pallas_attention.py --size 4096 --causal
"""

from __future__ import annotations

import itertools
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from _autotune_common import (  # noqa: E402
    MXU_PEAK_TFLOPS,
    build_parser,
    measure_median,
    setup_backend,
    write_report,
)

BQS = (256, 512, 1024)
BKS = (256, 512, 1024)


def main(argv=None) -> int:
    p = build_parser(
        __doc__, default_size=8192, default_report="AUTOTUNE_ATTENTION.md"
    )
    p.add_argument("--heads", type=int, default=8)
    # 128 = the lane width; other values run the tier's fallback, which
    # there is no point tuning.
    p.add_argument("--d-head", type=int, default=128)
    p.add_argument("--causal", action="store_true")
    args = p.parse_args(argv)
    if args.d_head % 128:
        print("--d-head must be a 128-lane multiple (the kernel's tiling "
              "requirement; other head sizes use the untiled fallback)",
              file=sys.stderr)
        return 2
    on_tpu = setup_backend(args)
    if on_tpu is None:
        return 1

    import jax
    import jax.numpy as jnp

    from matvec_mpi_multiplier_tpu.ops.pallas_attention import (
        _pallas_partial,
        _reference_partial,
    )
    from matvec_mpi_multiplier_tpu.utils.errors import TimingError

    s, h, d = args.size, args.heads, args.d_head
    dtype = args.dtype
    scale = 1.0 / (d ** 0.5)

    # Head-major operands generated on device (bench.py's fill pattern),
    # Q pre-scaled as the schedules do; K and V stacked into one array so
    # the two-operand timing harness (time_fn_looped) carries them.
    @jax.jit
    def gen():
        i1 = jax.lax.broadcasted_iota(jnp.int32, (h, s, d), 1)
        i2 = jax.lax.broadcasted_iota(jnp.int32, (h, s, d), 2)
        base = ((i1 + i2) % 1024).astype(dtype) * (10.0 / 1024.0)
        q = (base * jnp.asarray(scale, dtype)).astype(dtype)
        kv = jnp.stack([base, base * jnp.asarray(0.5, dtype)])
        return q, kv

    q, kv = gen()
    jax.block_until_ready((q, kv))
    pos = jnp.arange(s, dtype=jnp.int32)

    flops = 4.0 * s * s * h * d * (0.5 if args.causal else 1.0)

    def gflops(t: float) -> float:
        return flops / t / 1e9

    # Baseline: the xla tier's computation, from the tier's own tested
    # oracle (_reference_partial) rather than a re-implementation that
    # could drift from the kernel's masking/statistics conventions.
    @jax.jit
    def xla_attention(q_, kv_):
        o, _, l = _reference_partial(
            q_, kv_[0], kv_[1], pos, pos, causal=args.causal
        )
        return o / jnp.maximum(l, 1e-30)[..., None]

    rows = []
    try:
        t_xla = measure_median(xla_attention, (q, kv), args)
    except TimingError as e:
        t_xla = None
        rows.append(("xla tier", None, None, "unmeasurable"))
        print(f"xla: UNMEASURABLE ({e})", flush=True)
    else:
        rows.append(("xla tier", t_xla, gflops(t_xla), "ok"))
        print(f"xla: {t_xla*1e3:.3f} ms  {gflops(t_xla):.1f} GFLOP/s",
              flush=True)

    best = None
    for bq, bk in itertools.product(BQS, BKS):
        label = f"flash {bq}x{bk}"
        if s % bq or s % bk:
            rows.append((label, None, None, "indivisible"))
            continue

        def flash(q_, kv_, bq=bq, bk=bk):
            o, _, l = _pallas_partial(
                q_, kv_[0], kv_[1], pos, pos,
                causal=args.causal, bq=bq, bk=bk, interpret=not on_tpu,
            )
            return o / jnp.maximum(l, 1e-30)[..., None]

        try:
            t = measure_median(flash, (q, kv), args)
        except TimingError as e:
            rows.append((label, None, None, "unmeasurable"))
            print(f"{label}: UNMEASURABLE ({e})", flush=True)
            continue
        except Exception as e:  # compile failure — record and move on
            rows.append((label, None, None, f"{type(e).__name__}"))
            print(f"{label}: FAILED {type(e).__name__}", flush=True)
            continue
        rows.append((label, t, gflops(t), "ok"))
        print(f"{label}: {t*1e3:.3f} ms  {gflops(t):.1f} GFLOP/s",
              flush=True)
        if best is None or t < best[1]:
            best = (label, t)

    report = [
        "# Pallas flash-attention tile autotune",
        "",
        f"s={s}, h={h}, d_head={d}, {dtype} storage / fp32 statistics, "
        f"causal={args.causal}; device-looped measure ({args.n_reps} reps "
        f"× {args.samples} samples, median), backend="
        f"{'tpu' if on_tpu else 'interpret (smoke only)'} "
        "(generated by `scripts/autotune_pallas_attention.py`).",
        "",
        "| config | time (ms) | GFLOP/s | status |",
        "|---|---|---|---|",
    ]
    for label, t, gf, status in rows:
        report.append(
            f"| {label} | {t*1e3:.3f} | {gf:.1f} | {status} |"
            if t is not None else f"| {label} | — | — | {status} |"
        )
    if best is not None:
        baseline = (
            f"xla-tier baseline {gflops(t_xla):.1f} GFLOP/s"
            if t_xla is not None else "xla-tier baseline unmeasurable"
        )
        report += [
            "",
            f"Best tile: **{best[0]}** at {gflops(best[1]):.1f} GFLOP/s "
            f"({100*gflops(best[1])/(MXU_PEAK_TFLOPS*1e3):.2f}% of the "
            f"{MXU_PEAK_TFLOPS:.0f} TFLOP/s v5e "
            f"bf16 MXU peak); {baseline}. If the winner differs from the "
            "committed DEFAULT_BQ/DEFAULT_BK "
            "(`ops/pallas_attention.py`), update them and re-run the "
            "attention stage.",
        ]
    write_report("\n".join(report) + "\n", args)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Shared skeleton for the Pallas tile autotuners.

The three autotuners (scripts/autotune_pallas.py — HBM-bound GEMV tiles;
scripts/autotune_pallas_gemm.py — MXU-bound GEMM tiles;
scripts/autotune_pallas_attention.py — the fused attention tile) share
their CLI, platform guard, candidate timing, and report-writing logic;
this module holds it once so a fix to one face (e.g. the platform
override or the TimingError path) cannot silently drift from the others.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# v5e per-chip bf16 MXU peak, the denominator of every %-of-peak/MFU line
# (same convention as scripts/stats_visualization.py --mxu-peak).
MXU_PEAK_TFLOPS = 197.0


def build_parser(doc: str, *, default_size: int, default_report: str
                 ) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=doc)
    p.add_argument("--size", type=int, default=default_size)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--n-reps", type=int, default=20)
    p.add_argument("--samples", type=int, default=3)
    p.add_argument("--allow-interpret", action="store_true")
    p.add_argument("--platform", default=None,
                   help="jax platform override (e.g. cpu for smoke tests; "
                   "the env var alone is outranked by the preinstalled "
                   "accelerator plugin's jax.config pin)")
    p.add_argument("--report", default=str(REPO / "docs" / default_report))
    p.add_argument("--no-report", action="store_true")
    return p


def setup_backend(args: argparse.Namespace) -> bool | None:
    """Apply the platform override and enforce the TPU-only default.

    Returns ``on_tpu``, or None when the script must exit (off-TPU without
    --allow-interpret: interpret-mode pallas at real sizes would effectively
    hang).
    """
    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform

    configure_platform(args.platform, None)
    from matvec_mpi_multiplier_tpu.ops.pallas_gemv import _on_tpu

    on_tpu = _on_tpu()
    if not on_tpu and not args.allow_interpret:
        print("not on TPU (pallas would run in interpret mode); "
              "pass --allow-interpret --size <small> to smoke-test",
              file=sys.stderr)
        return None
    return on_tpu


def measure_median(fn, operands, args: argparse.Namespace) -> float:
    """Median device-looped slope for one candidate (TimingError propagates
    to the caller, which records the candidate as unmeasurable/failed)."""
    import numpy as np

    from matvec_mpi_multiplier_tpu.bench.timing import time_fn_looped

    return float(np.median(time_fn_looped(
        fn, operands, n_reps=args.n_reps, samples=args.samples,
    )))


def write_report(text: str, args: argparse.Namespace) -> None:
    print("\n" + text)
    if not args.no_report:
        out = Path(args.report)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(text)
        print(f"wrote {out}")

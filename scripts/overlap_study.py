#!/usr/bin/env python
"""Overlap-schedule study: colwise_ring vs colwise_ring_overlap, with evidence.

Round-2 review finding: the overlap claim of ``ring_matvec``
(``parallel/ring.py``) was a docstring, proven only bit-identical to the
non-overlapped ring — correctness, not scheduling. This study produces the
evidence:

1. **Compiled-schedule analysis** — lowers both variants through the real
   backend compiler and extracts the linear order of collective-permute and
   dot/fusion ops from the optimized HLO. The overlapped schedule must show
   compute INTERLEAVED between permute hops (permute, dot, permute, dot, ...)
   where the non-overlapped one computes everything first, then permutes
   (dot, permute, permute, ...). On TPU the permutes additionally appear as
   async ``collective-permute-start``/``-done`` pairs; ops issued between a
   start and its done execute concurrently with the transfer — that pair
   distance is the overlap, counted here.
2. **Timing comparison** — the benchmark protocol (sync measure) on the same
   mesh, recording where the explicit schedule wins or loses.
3. Optional **profiler trace** (``--profile-dir``) of both variants for
   TensorBoard/Perfetto inspection.

Writes a markdown report (default ``docs/OVERLAP.md``) and prints it.

Usage::

    python scripts/overlap_study.py --platform cpu --host-devices 8
    python scripts/overlap_study.py                      # real backend (TPU)
"""

from __future__ import annotations

import argparse
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

VARIANTS = ("colwise_ring", "colwise_ring_overlap")


def _flatten(jaxpr, eqns: list, alias: dict) -> None:
    """Flatten equations across sub-jaxprs (shard_map/pjit bodies), recording
    variable aliases at each boundary so transitive dependencies survive:
    an inner body's invars are fresh Var objects positionally bound to the
    outer equation's invars, and the outer outvars to the body's outvars —
    without these links a dot inside a jitted kernel would look independent
    of everything outside it."""
    for eqn in jaxpr.eqns:
        sub = None
        for val in eqn.params.values():
            inner = val if hasattr(val, "eqns") else getattr(val, "jaxpr", None)
            # A ClosedJaxpr (old-JAX shard_map carries one in its params)
            # exposes .eqns but not .invars — unwrap to the raw Jaxpr.
            inner = getattr(inner, "jaxpr", inner)
            if hasattr(inner, "eqns"):
                sub = inner
                break
        if sub is not None:
            n = min(len(sub.invars), len(eqn.invars))
            for inner_v, outer_v in zip(sub.invars[-n:], eqn.invars[-n:]):
                alias[id(inner_v)] = outer_v
            _flatten(sub, eqns, alias)
            m = min(len(eqn.outvars), len(sub.outvars))
            for outer_v, inner_v in zip(eqn.outvars[-m:], sub.outvars[-m:]):
                alias[id(outer_v)] = inner_v
        else:
            eqns.append(eqn)


def overlap_stats(fn, a, x) -> dict:
    """Dependency analysis of the ring schedule on the jaxpr.

    The overlap property is structural, not textual: a permute hop and a
    tile-GEMV can execute concurrently iff neither is a (transitive)
    data-dependency ancestor of the other. In ``ring_matvec`` every step's
    tile dot reads only the resident panel + x segment, so it is mutually
    independent of that step's ``ppermute`` — the scheduler MAY overlap
    them. In ``ring_psum_scatter`` the single local-partial dot is an
    ancestor of every permute (the accumulator being permuted IS its
    output), so no (permute, dot) pair can overlap. Counting mutually
    independent pairs therefore separates the two schedules exactly, on any
    backend, without trusting HLO print order.
    """
    import jax

    jaxpr = jax.make_jaxpr(fn)(a, x)
    eqns: list = []
    alias: dict = {}
    _flatten(jaxpr.jaxpr, eqns, alias)

    def canon(v) -> int:
        while id(v) in alias:
            v = alias[id(v)]
        return id(v)

    produced: dict = {}
    deps: list[set] = []
    for i, eqn in enumerate(eqns):
        d: set = set()
        for v in eqn.invars:
            if not hasattr(v, "aval") or type(v).__name__ == "Literal":
                continue
            j = produced.get(canon(v))
            if j is not None:
                d.add(j)
                d |= deps[j]
        deps.append(d)
        for v in eqn.outvars:
            produced[canon(v)] = i
    permutes = [i for i, e in enumerate(eqns) if e.primitive.name == "ppermute"]
    dots = [i for i, e in enumerate(eqns) if e.primitive.name == "dot_general"]
    concurrent = {
        p: [d for d in dots if p not in deps[d] and d not in deps[p]]
        for p in permutes
    }
    return {
        "n_permute": len(permutes),
        "n_dot": len(dots),
        "hops_with_concurrent_dot": sum(1 for v in concurrent.values() if v),
        "concurrent_pairs": sum(len(v) for v in concurrent.values()),
    }


# TPU async evidence: the compiled module emits collective-permute-start/
# -done pairs; compute scheduled between them runs during the transfer.
# Match the OPCODE position only (space before, '(' immediately after): the
# defining line's instruction name ('%collective-permute-start.1 = ...') is
# preceded by '%', and operand references carry a '.N)' suffix — neither
# matches, so each real pair counts exactly once.
def async_pair_stats(hlo: str) -> dict:
    starts = len(re.findall(r" collective-permute-start\(", hlo))
    dones = len(re.findall(r" collective-permute-done\(", hlo))
    return {"async_starts": starts, "async_dones": dones}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--platform", default=None)
    p.add_argument("--host-devices", type=int, default=None)
    p.add_argument("--size", type=int, default=4096)
    p.add_argument("--dtype", default="float32")
    p.add_argument("--n-reps", type=int, default=25)
    p.add_argument("--devices", type=int, default=None,
                   help="mesh size (default: all available)")
    p.add_argument("--profile-dir", default=None)
    p.add_argument("--report", default=str(REPO / "docs" / "OVERLAP.md"))
    p.add_argument("--no-report", action="store_true")
    args = p.parse_args(argv)

    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform

    configure_platform(args.platform, args.host_devices)

    import jax
    import numpy as np

    from matvec_mpi_multiplier_tpu.bench.profiling import annotate, trace
    from matvec_mpi_multiplier_tpu.bench.timing import time_matvec
    from matvec_mpi_multiplier_tpu.models import get_strategy
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh

    n_dev = args.devices or len(jax.devices())
    if n_dev < 2:
        # p=1 short-circuits the ring (zero permute hops): the study would
        # produce an empty schedule table and clobber a meaningful report.
        print(
            "overlap study needs >= 2 devices (ring has no hops at p=1); "
            "nothing to measure on this backend — skipping",
            file=sys.stderr,
        )
        return 0
    mesh = make_mesh(n_dev)
    platform = jax.devices()[0].platform
    n = args.size
    rng = np.random.default_rng(7)
    a = rng.standard_normal((n, n)).astype(args.dtype)
    x = rng.standard_normal(n).astype(args.dtype)

    rows = []
    for name in VARIANTS:
        strat = get_strategy(name)
        fn = strat.build(mesh)
        stats = overlap_stats(fn, a, x)
        # This explicit compile seeds fn's jit cache (verified: the timed
        # calls below hit it), so the study compiles each variant once.
        stats.update(async_pair_stats(fn.lower(a, x).compile().as_text()))
        with trace(args.profile_dir, enabled=args.profile_dir is not None):
            with annotate(name):
                times = time_matvec(
                    fn, a, x, shardings=strat.shardings(mesh),
                    n_reps=args.n_reps, measure="sync",
                )
        mean_s = float(np.mean(times))
        rows.append((name, mean_s, stats))
        print(f"{name}: {mean_s*1e3:.3f} ms  {stats}")

    base, over = rows
    ratio = over[1] / base[1]
    report = [
        "# Overlap schedule study: `colwise_ring` vs `colwise_ring_overlap`",
        "",
        f"Backend: **{platform}**, {n_dev}-device mesh "
        f"{tuple(mesh.shape.values())}, size {n}² {args.dtype}, "
        f"sync measure, {args.n_reps} reps "
        f"(generated by `scripts/overlap_study.py`).",
        "",
        "| variant | time (ms) | permute hops | dots | hops with a "
        "concurrent dot | independent (permute, dot) pairs | async "
        "start/done in compiled HLO |",
        "|---|---|---|---|---|---|---|",
    ]
    for name, mean_s, stats in rows:
        report.append(
            f"| {name} | {mean_s*1e3:.3f} | {stats['n_permute']} | "
            f"{stats['n_dot']} | {stats['hops_with_concurrent_dot']} | "
            f"{stats['concurrent_pairs']} | "
            f"{stats['async_starts']}/{stats['async_dones']} |"
        )
    report += [
        "",
        f"Overlapped/non-overlapped time ratio: **{ratio:.2f}×** "
        f"({'overlap wins' if ratio < 1 else 'overlap loses'} on this "
        "backend/mesh).",
        "",
        "**What the columns prove.** Overlap is a structural property of "
        "the dataflow, measured here by transitive-dependency analysis on "
        "the jaxpr (`overlap_stats`): a permute hop and a dot can execute "
        "concurrently iff neither is an ancestor of the other. "
        "`colwise_ring_overlap` (`parallel/ring.py:ring_matvec`) reads each "
        "step's GEMV tile from the resident column panel, so **every hop "
        "has compute it can overlap with** — the scheduler is free to run "
        "the tile-GEMV while the previous hop's `ppermute` is in flight. "
        "The non-overlapped `ring_psum_scatter` materializes the full local "
        "partial in one dot whose output IS the accumulator being permuted: "
        "every permute depends on it, zero pairs are independent, and no "
        "overlap is possible even in principle. On TPU the compiled module "
        "additionally emits async `collective-permute-start`/`-done` pairs "
        "(last column) — the hardware mechanism that realizes the overlap; "
        "the CPU backend lowers permutes synchronously and serializes "
        "everything onto one stream, so there the timing shows the "
        "schedule's *cost* (p unrolled steps of small tiles) without its "
        "*benefit*: the committed CPU-mesh ladder has the unrolled schedule "
        "losing 5-8× on an oversubscribed virtual mesh (README §Results). "
        "Explicit overlap machinery pays only on hardware with real "
        "parallel links.",
    ]
    text = "\n".join(report) + "\n"
    print("\n" + text)
    if not args.no_report:
        out = Path(args.report)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(text)
        print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/bin/bash
# Poll the tunneled TPU until it answers a probe, then run the full capture.
#
# The tunnel wedges unpredictably (jax.devices() blocks in C++; see
# BASELINE.json's blockwise_65536_bf16_hbm_sweep.mapping_note). This watcher
# turns "attempt the capture first thing, every session" (VERDICT.md round-2,
# next-round item 1) into a standing loop: probe every $INTERVAL seconds with
# a hard timeout, and on the first healthy probe hand off to
# scripts/tpu_measure_all.py (which re-probes itself and flushes per stage).
#
# Usage: nohup bash scripts/watch_and_capture.sh [capture args...] &
set -u
cd "$(dirname "$0")/.."
INTERVAL="${WATCH_INTERVAL_S:-180}"
PROBE_TIMEOUT="${WATCH_PROBE_TIMEOUT_S:-120}"
while true; do
  if timeout "$PROBE_TIMEOUT" python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) probe OK — starting capture" >&2
    python scripts/tpu_measure_all.py "$@"
    exit $?
  fi
  echo "$(date -u +%FT%TZ) probe failed/hung — retrying in ${INTERVAL}s" >&2
  sleep "$INTERVAL"
done

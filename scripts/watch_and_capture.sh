#!/bin/bash
# Poll the tunneled TPU until it answers a probe, then run the full capture —
# and if the capture itself dies mid-run (tunnel wedge), go back to probing
# and try again at the next healthy window. By default it retries FOREVER
# (WATCH_MAX_ATTEMPTS=0): observed wedges run 8+ hours, so any finite budget
# risks sitting idle through the one healthy window that matters. Failed
# probes never count against the budget — only started captures do.
#
# The tunnel wedges unpredictably (jax.devices() blocks in C++; see
# BASELINE.json's blockwise_65536_bf16_hbm_sweep.mapping_note). This watcher
# turns "attempt the capture first thing, every session" (VERDICT.md round-2,
# next-round item 1) into a standing loop: probe every $INTERVAL seconds with
# a hard timeout, and on a healthy probe hand off to
# scripts/tpu_measure_all.py (which re-probes itself, runs stages
# highest-leverage-first, and flushes results per stage — so a retry only
# re-does cheap early stages, with the XLA compile cache amortizing repeats).
#
# Usage: nohup bash scripts/watch_and_capture.sh [capture args...] &
set -u
cd "$(dirname "$0")/.."
INTERVAL="${WATCH_INTERVAL_S:-180}"
PROBE_TIMEOUT="${WATCH_PROBE_TIMEOUT_S:-120}"
MAX_ATTEMPTS="${WATCH_MAX_ATTEMPTS:-0}"   # 0 = unlimited
attempt=0
while [ "$MAX_ATTEMPTS" -eq 0 ] || [ "$attempt" -lt "$MAX_ATTEMPTS" ]; do
  if [ "$MAX_ATTEMPTS" -eq 0 ] && [ "$attempt" -ge 1000 ]; then
    break  # runaway backstop far above any real session
  fi
  if timeout "$PROBE_TIMEOUT" python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    attempt=$((attempt + 1))
    echo "$(date -u +%FT%TZ) probe OK — capture attempt $attempt/${MAX_ATTEMPTS/#0/inf}" >&2
    # --wipe-stale-csvs (if given) passes through on EVERY attempt: the
    # capture's wipe is once-per-round via a sentinel (.stale_wiped, see
    # tpu_measure_all.py), so a retry resumes over the partial dataset an
    # earlier attempt flushed (sweep stages pass --skip-measured) instead
    # of setting it aside and redoing every config.
    python scripts/tpu_measure_all.py "$@"
    rc=$?
    if [ "$rc" -eq 0 ]; then
      echo "$(date -u +%FT%TZ) capture succeeded on attempt $attempt" >&2
      exit 0
    elif [ "$rc" -ne 1 ]; then
      # Anything but the explicit retryable abort (rc=1: probe failure,
      # wedge timeout, a sweep that completed with transient config
      # failures, or the baseline degrading to the cpu fallback) is
      # deterministic — completed-with-hard-failed-stages (rc=4),
      # argparse usage errors (rc=2, e.g. a typo'd flag passed through
      # "$@"), crashes. Retrying the whole capture cannot heal those and
      # would burn the healthy window in a loop (retries of the
      # retryable class are cheap: sweeps resume via --skip-measured).
      echo "$(date -u +%FT%TZ) capture attempt $attempt ended rc=$rc (deterministic; only rc=1 retries) — not retrying; see report above" >&2
      exit 2
    fi
    echo "$(date -u +%FT%TZ) capture attempt $attempt aborted (rc=1, wedge/probe) — back to probing" >&2
  else
    echo "$(date -u +%FT%TZ) probe failed/hung — retrying in ${INTERVAL}s" >&2
  fi
  sleep "$INTERVAL"
done
echo "$(date -u +%FT%TZ) giving up after $attempt capture attempts" >&2
exit 1

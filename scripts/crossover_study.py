#!/usr/bin/env python
"""Roofline-crossover study: where GEMV becomes GEMM on the MXU.

The reference's entire scope is ``n_rhs = 1`` (``y = A·x``,
``src/matr_utils.c:86-96``) — the memory-bound corner of the roofline,
where the committed sweeps show this framework at ~92% of HBM peak. This
study measures what the reference never could: the transition from the
HBM-bound GEMV regime to the MXU-bound GEMM regime as right-hand sides
are added, on the same blockwise strategy and the same chip.

Model: for C = A·B with A (n×n) and B (n×r), bf16, arithmetic intensity
is I(r) = 2n²r / 2(n² + 2nr) ≈ r FLOP/byte for r ≪ n. The v5e ridge
point sits at I* = MXU_PEAK / HBM_PEAK ≈ 197e3/819 ≈ 240 FLOP/byte, so
the knee should appear near r ≈ 240 — the study sweeps r over powers of
two and reports, per r: measured time, effective GB/s (HBM axis),
achieved GFLOP/s and MFU (MXU axis), and which roofline bound is closer.
The measured knee pins the chip's actual ridge against the datasheet
one; everything is appended to the extended CSV (strategy label
``gemm_blockwise_xover``, one row per r, distinguished by the schema's
``n_rhs`` column) so the data-quality gates cover it.

Usage::

    python scripts/crossover_study.py                      # real chip
    python scripts/crossover_study.py --platform cpu --host-devices 8 \
        --size 512 --n-rhs 1 8 64                          # plumbing test
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

DEFAULT_RHS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--platform", default=None)
    p.add_argument("--host-devices", type=int, default=None)
    p.add_argument("--size", type=int, default=8192)
    p.add_argument("--n-rhs", type=int, nargs="*", default=list(DEFAULT_RHS))
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--n-reps", type=int, default=20)
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--data-root", default=None)
    p.add_argument("--no-csv", action="store_true")
    p.add_argument("--hbm-peak-gbps", type=float, default=None,
                   help="HBM roofline (default: utils.constants for TPU)")
    p.add_argument("--mxu-peak-gflops", type=float, default=None,
                   help="MXU roofline (default: utils.constants for TPU)")
    p.add_argument("--report", default=str(REPO / "docs" / "CROSSOVER.md"))
    p.add_argument("--no-report", action="store_true")
    args = p.parse_args(argv)

    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform

    configure_platform(args.platform, args.host_devices)

    import dataclasses

    import jax
    import numpy as np

    from matvec_mpi_multiplier_tpu.bench.metrics import append_result
    from matvec_mpi_multiplier_tpu.bench.timing import benchmark_gemm
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.utils import constants
    from matvec_mpi_multiplier_tpu.utils.errors import TimingError

    platform = jax.devices()[0].platform
    n_dev = args.devices or len(jax.devices())
    mesh = make_mesh(n_dev)
    hbm = args.hbm_peak_gbps or constants.TPU_HBM_PEAK_GBPS * n_dev
    # The MXU peak (and hence the ridge and MFU columns) is the bf16 one;
    # for other dtypes the bound is annotated as nominal in the report.
    mxu = args.mxu_peak_gflops or constants.MXU_PEAK_BF16_GFLOPS * n_dev
    ridge = mxu / hbm
    itemsize = constants.DTYPE_ITEMSIZE[args.dtype]
    n = args.size
    rng = np.random.default_rng(7)
    a = rng.standard_normal((n, n)).astype(np.float32)

    rows = []
    for r in sorted(set(args.n_rhs)):
        b = rng.standard_normal((n, r)).astype(np.float32)
        res = None
        for attempt in (1, 2):
            try:
                res = benchmark_gemm(
                    "blockwise", mesh, a, b, dtype=args.dtype,
                    n_reps=args.n_reps, measure="loop",
                )
                break
            except TimingError as e:
                print(f"n_rhs={r} attempt {attempt}: UNMEASURABLE ({e})",
                      file=sys.stderr)
        if res is None:
            rows.append((r, None))
            continue
        if not args.no_csv:
            # Own label PER r: downstream per-strategy-CSV consumers
            # (analysis/stats.py) average rows sharing (strategy, m, n, p)
            # — a shared xover label would blend every r into one
            # nonsense series. results_extended keeps n_rhs either way.
            append_result(
                dataclasses.replace(
                    res, strategy=f"gemm_blockwise_xover_r{r}"
                ),
                args.data_root,
            )
        intensity = 2.0 * res.n_rows * res.n_cols * res.n_rhs / (
            itemsize * (res.n_rows * res.n_cols
                        + res.n_cols * res.n_rhs
                        + res.n_rows * res.n_rhs)
        )  # FLOP per byte: 2mkr / itemsize·(mk + kr + mr)
        mfu = res.gflops / mxu
        rows.append((r, dict(
            time_ms=res.mean_time_s * 1e3, gbps=res.gbps,
            gflops=res.gflops, mfu=mfu, intensity=intensity,
            hbm_frac=res.gbps / hbm,
        )))
        print(f"n_rhs={r:5d}: {res.mean_time_s*1e3:9.3f} ms  "
              f"{res.gbps:8.2f} GB/s ({res.gbps/hbm:5.1%} HBM)  "
              f"{res.gflops/1e3:9.2f} TFLOP/s (MFU {mfu:6.2%})")

    measured = [(r, m) for r, m in rows if m is not None]
    knee = None
    for r, m in measured:
        # The empirical knee: first r where the compute axis dominates the
        # bandwidth axis (MFU fraction exceeds HBM fraction).
        if m["mfu"] >= m["hbm_frac"]:
            knee = r
            break

    report = [
        "# GEMV→GEMM roofline crossover (measured)",
        "",
        f"Backend: **{platform}**, {n_dev}-device mesh, blockwise strategy, "
        f"A {n}×{n} {args.dtype}, B {n}×r, measure=loop, {args.n_reps} reps "
        "(generated by `scripts/crossover_study.py`).",
        "",
        f"Rooflines used: HBM {hbm:.0f} GB/s, MXU {mxu/1e3:.0f} TFLOP/s"
        + (" (bf16 peak — nominal for this dtype)"
           if args.dtype != "bfloat16" else "")
        + f" → ridge intensity {ridge:.0f} FLOP/byte; model "
        f"I(r) ≈ 2r/{itemsize} for r ≪ n predicts the knee near "
        f"r ≈ {ridge * itemsize / 2:.0f}.",
        "",
        "| n_rhs | I(r) FLOP/B | time (ms) | GB/s | %HBM | TFLOP/s | MFU |",
        "|---|---|---|---|---|---|---|",
    ]
    for r, m in rows:
        if m is None:
            report.append(f"| {r} | — | unmeasurable | — | — | — | — |")
        else:
            report.append(
                f"| {r} | {m['intensity']:.1f} | {m['time_ms']:.3f} | "
                f"{m['gbps']:.1f} | {m['hbm_frac']:.1%} | "
                f"{m['gflops']/1e3:.2f} | {m['mfu']:.2%} |"
            )
    report += [
        "",
        (f"Measured knee (first r where MFU ≥ %HBM): **r = {knee}** vs the "
         f"datasheet ridge r ≈ {ridge * itemsize / 2:.0f}."
         if knee is not None else
         "No measured knee inside the swept range — every row is still "
         "bandwidth-bound (or unmeasurable this window)."),
        "",
        "Reading: at r = 1 this is the reference's workload — pure HBM "
        "streaming, the MXU nearly idle. Each doubling of r doubles "
        "arithmetic intensity at almost constant traffic, so time stays "
        "flat and TFLOP/s doubles until the MXU saturates; past the knee, "
        "time scales with r and %HBM falls. The same A·x engine the "
        "reference benchmarks is, on this hardware, one axis of a GEMM "
        "whose other axis is free until r ≈ the ridge — the quantitative "
        "case for batching right-hand sides on TPU.",
    ]
    text = "\n".join(report) + "\n"
    print("\n" + text)
    if not args.no_report:
        out = Path(args.report)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(text)
        print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

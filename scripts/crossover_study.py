#!/usr/bin/env python
"""Roofline-crossover study: where GEMV becomes GEMM on the MXU.

The reference's entire scope is ``n_rhs = 1`` (``y = A·x``,
``src/matr_utils.c:86-96``) — the memory-bound corner of the roofline,
where the committed sweeps show this framework at ~92% of HBM peak. This
study measures what the reference never could: the transition from the
HBM-bound GEMV regime to the MXU-bound GEMM regime as right-hand sides
are added, on the same blockwise strategy and the same chip.

Model: for C = A·B with A (n×n) and B (n×r), bf16, arithmetic intensity
is I(r) = 2n²r / 2(n² + 2nr) ≈ r FLOP/byte for r ≪ n. The v5e ridge
point sits at I* = MXU_PEAK / HBM_PEAK ≈ 197e3/819 ≈ 240 FLOP/byte, so
the knee should appear near r ≈ 240 — the study sweeps r over powers of
two and reports, per r: measured time, its excess over the
bandwidth-model time anchored at the measured r=1 row (the measured-knee
criterion — the roofline fractions share one measured time, so only the
time-vs-byte-model excess carries chip information), effective GB/s (HBM
axis), and achieved GFLOP/s / MFU (MXU axis). Everything is appended to
the extended CSV (one ``gemm_blockwise_xover_r<r>`` label per r so no
downstream consumer averages across r) so the data-quality gates cover
it.

Usage::

    python scripts/crossover_study.py                      # real chip
    python scripts/crossover_study.py --platform cpu --host-devices 8 \
        --size 512 --n-rhs 1 8 64                          # plumbing test
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

DEFAULT_RHS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--platform", default=None)
    p.add_argument("--host-devices", type=int, default=None)
    p.add_argument("--size", type=int, default=8192)
    p.add_argument("--n-rhs", type=int, nargs="*", default=list(DEFAULT_RHS))
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--n-reps", type=int, default=20)
    # "loop" (the chip protocol: device-side rep loop + adaptive rep
    # spread) is the default for captures; "sync" is the light protocol
    # for CI on oversubscribed virtual meshes, where the loop protocol's
    # spread search over 8-thread collectives on too few cores can stall
    # on collective-rendezvous spin (tests pass --measure sync — they pin
    # the CLI/report mechanics, not chip timing).
    p.add_argument("--measure", default="loop",
                   choices=("loop", "sync", "chain"))
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--data-root", default=None)
    p.add_argument("--no-csv", action="store_true")
    p.add_argument("--hbm-peak-gbps", type=float, default=None,
                   help="PER-CHIP HBM roofline, scaled by the device count "
                   "like the default (utils.constants for TPU)")
    p.add_argument("--mxu-peak-gflops", type=float, default=None,
                   help="PER-CHIP MXU roofline, scaled by the device count "
                   "like the default (utils.constants for TPU)")
    p.add_argument("--report", default=str(REPO / "docs" / "CROSSOVER.md"))
    p.add_argument("--no-report", action="store_true")
    p.add_argument("--fig",
                   default=str(REPO / "figures" / "tpu" / "crossover.png"))
    p.add_argument("--no-fig", action="store_true")
    args = p.parse_args(argv)

    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform

    configure_platform(args.platform, args.host_devices)

    import dataclasses

    import jax
    import numpy as np

    from matvec_mpi_multiplier_tpu.bench.metrics import append_result
    from matvec_mpi_multiplier_tpu.bench.timing import benchmark_gemm
    from matvec_mpi_multiplier_tpu.parallel.mesh import make_mesh
    from matvec_mpi_multiplier_tpu.utils import constants
    from matvec_mpi_multiplier_tpu.utils.errors import TimingError

    platform = jax.devices()[0].platform
    n_dev = args.devices or len(jax.devices())
    mesh = make_mesh(n_dev)
    hbm = (constants.TPU_HBM_PEAK_GBPS if args.hbm_peak_gbps is None
           else args.hbm_peak_gbps) * n_dev
    # The MXU peak (and hence the ridge and MFU columns) is the bf16 one;
    # for other dtypes the bound is annotated as nominal in the report.
    mxu = (constants.MXU_PEAK_BF16_GFLOPS if args.mxu_peak_gflops is None
           else args.mxu_peak_gflops) * n_dev
    ridge = mxu / hbm
    itemsize = constants.DTYPE_ITEMSIZE[args.dtype]
    n = args.size
    rng = np.random.default_rng(7)
    a = rng.standard_normal((n, n)).astype(np.float32)

    rows = []
    for r in sorted(set(args.n_rhs)):
        b = rng.standard_normal((n, r)).astype(np.float32)
        res = None
        for attempt in (1, 2):
            try:
                res = benchmark_gemm(
                    "blockwise", mesh, a, b, dtype=args.dtype,
                    n_reps=args.n_reps, measure=args.measure,
                )
                break
            except TimingError as e:
                print(f"n_rhs={r} attempt {attempt}: UNMEASURABLE ({e})",
                      file=sys.stderr)
        if res is None:
            rows.append((r, None))
            continue
        if not args.no_csv:
            # Own label PER r: downstream per-strategy-CSV consumers
            # (analysis/stats.py) average rows sharing (strategy, m, n, p)
            # — a shared xover label would blend every r into one
            # nonsense series. results_extended keeps n_rhs either way.
            append_result(
                dataclasses.replace(
                    res, strategy=f"gemm_blockwise_xover_r{r}"
                ),
                args.data_root,
            )
        bytes_r = itemsize * (res.n_rows * res.n_cols
                              + res.n_cols * res.n_rhs
                              + res.n_rows * res.n_rhs)
        # FLOP per byte: 2mkr / itemsize·(mk + kr + mr)
        intensity = 2.0 * res.n_rows * res.n_cols * res.n_rhs / bytes_r
        mfu = res.gflops / mxu
        rows.append((r, dict(
            time_ms=res.mean_time_s * 1e3, gbps=res.gbps,
            gflops=res.gflops, mfu=mfu, intensity=intensity,
            hbm_frac=res.gbps / hbm, bytes=bytes_r,
        )))
        print(f"n_rhs={r:5d}: {res.mean_time_s*1e3:9.3f} ms  "
              f"{res.gbps:8.2f} GB/s ({res.gbps/hbm:5.1%} HBM)  "
              f"{res.gflops/1e3:9.2f} TFLOP/s (MFU {mfu:6.2%})")

    measured = [(r, m) for r, m in rows if m is not None]
    # The MEASURED knee must come from quantities that don't cancel: the
    # roofline columns (%HBM, MFU) share the same measured time, so
    # comparing them reduces to shapes-and-datasheet algebra, not to what
    # the chip did. The genuinely measured signal is time(r): while
    # bandwidth-bound it tracks the byte model anchored at the measured
    # r=1 bandwidth (bytes grow only ~1+2r/n), and at the compute-bound
    # transition it departs upward. Knee = first r whose measured time
    # exceeds that anchored bandwidth prediction by >=KNEE_EXCESS.
    KNEE_EXCESS = 1.5
    knee = None
    anchor_state = ("ok" if measured and measured[0][0] == 1
                    else "unmeasurable" if rows and rows[0][0] == 1
                    else "not swept")
    if anchor_state == "ok":
        t1, b1 = measured[0][1]["time_ms"], measured[0][1]["bytes"]
        for r, m in measured[1:]:
            m["excess"] = m["time_ms"] / (t1 * m["bytes"] / b1)
            if knee is None and m["excess"] >= KNEE_EXCESS:
                knee = r

    report = [
        "# GEMV→GEMM roofline crossover (measured)",
        "",
        f"Backend: **{platform}**, {n_dev}-device mesh, blockwise strategy, "
        f"A {n}×{n} {args.dtype}, B {n}×r, measure={args.measure}, "
        f"{args.n_reps} reps "
        "(generated by `scripts/crossover_study.py`).",
        "",
        f"Rooflines used: HBM {hbm:.0f} GB/s, MXU {mxu/1e3:.0f} TFLOP/s"
        + (" (bf16 peak — nominal for this dtype)"
           if args.dtype != "bfloat16" else "")
        + f" → ridge intensity {ridge:.0f} FLOP/byte; model "
        f"I(r) ≈ 2r/{itemsize} for r ≪ n predicts the knee near "
        f"r ≈ {ridge * itemsize / 2:.0f}.",
        "",
        "| n_rhs | I(r) FLOP/B | time (ms) | t/t_bw(r) | GB/s | %HBM | "
        "TFLOP/s | MFU |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r, m in rows:
        if m is None:
            report.append(f"| {r} | — | unmeasurable | — | — | — | — | — |")
        else:
            excess = (f"{m['excess']:.2f}" if "excess" in m
                      else "1 (anchor)" if r == 1 else "—")
            report.append(
                f"| {r} | {m['intensity']:.1f} | {m['time_ms']:.3f} | "
                f"{excess} | {m['gbps']:.1f} | {m['hbm_frac']:.1%} | "
                f"{m['gflops']/1e3:.2f} | {m['mfu']:.2%} |"
            )
    report += [
        "",
        "t/t_bw(r) is the measured time over the bandwidth-model "
        "prediction anchored at the measured r = 1 row (bytes(r)/bytes(1) "
        "× t(1)) — the one column the datasheet cannot predetermine; the "
        "%HBM and MFU columns share one measured time, so comparing them "
        "to each other would merely restate the shape algebra.",
        "",
        (f"Measured knee (first r with t/t_bw ≥ {KNEE_EXCESS}): "
         f"**r = {knee}** vs the datasheet ridge "
         f"r ≈ {ridge * itemsize / 2:.0f}."
         if knee is not None
         else "No measured knee inside the swept range — every measured "
         "row still tracks the bandwidth model."
         if anchor_state == "ok"
         else "t/t_bw needs the r = 1 anchor, which was unmeasurable "
         "this window — no knee computable."
         if anchor_state == "unmeasurable"
         else "t/t_bw needs the r = 1 anchor — add 1 to --n-rhs to "
         "compute the measured knee."),
        "",
        "Reading: at r = 1 this is the reference's workload — pure HBM "
        "streaming, the MXU nearly idle. Each doubling of r doubles "
        "arithmetic intensity at almost constant traffic, so time stays "
        "flat and TFLOP/s doubles until the MXU saturates; past the knee, "
        "time scales with r and %HBM falls. The same A·x engine the "
        "reference benchmarks is, on this hardware, one axis of a GEMM "
        "whose other axis is free until r ≈ the ridge — the quantitative "
        "case for batching right-hand sides on TPU.",
    ]
    if not args.no_fig:
        # matplotlib lives in the [analysis] extra: its absence must cost
        # the figure, never the sweep's report (the measurements above may
        # have taken a whole healthy tunnel window).
        try:
            from matvec_mpi_multiplier_tpu.analysis.plots import (
                plot_crossover_roofline,
            )

            fig_path = plot_crossover_roofline(
                [(r, m["intensity"], m["gflops"]) for r, m in measured],
                args.fig, hbm_peak_gbps=hbm, mxu_peak_gflops=mxu,
            )
        except ImportError as e:
            print(f"figure skipped: {e}", file=sys.stderr)
            fig_path = None
        if fig_path is not None:
            try:
                shown = fig_path.relative_to(REPO)
            except ValueError:  # user-supplied --fig outside the repo
                shown = fig_path
            report += ["", f"Figure: `{shown}`."]
            print(f"figure: {fig_path}")
    text = "\n".join(report) + "\n"
    print("\n" + text)
    if not args.no_report:
        out = Path(args.report)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(text)
        print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Distributed least-squares solver CLI — the trainer's user-facing surface.

Solves ``min_x ||A x - b||^2`` by gradient descent with every array sharded
over the device mesh (models/trainer.py), checkpointing every ``--ckpt-every``
steps and resuming from the latest checkpoint if one exists.

Examples::

    python scripts/solve.py --size 512 256 --steps 200
    python scripts/solve.py --size 512 256 --steps 200 \
        --ckpt-dir /tmp/solve_ckpt --ckpt-every 50   # interrupt + rerun: resumes
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--size", nargs=2, type=int, default=[512, 256],
                   metavar=("M", "N"))
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--ckpt-every", type=int, default=50)
    p.add_argument("--platform", default=None,
                   help="jax platform override (e.g. cpu; the env var alone "
                   "is outranked by the preinstalled accelerator plugin's "
                   "jax.config pin)")
    p.add_argument("--host-devices", type=int, default=None,
                   help="virtual CPU device count (the mpiexec -n analog)")
    args = p.parse_args(argv)
    if args.ckpt_every < 1:
        p.error("--ckpt-every must be >= 1")

    from matvec_mpi_multiplier_tpu.bench.sweep import configure_platform

    configure_platform(args.platform, args.host_devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from matvec_mpi_multiplier_tpu import make_mesh
    from matvec_mpi_multiplier_tpu.models import trainer
    from matvec_mpi_multiplier_tpu.parallel import distributed
    from matvec_mpi_multiplier_tpu.utils import checkpoint

    distributed.initialize()
    mesh = make_mesh(args.devices)
    m, n = args.size
    rng = np.random.default_rng(args.seed)
    x_true = rng.standard_normal(n)
    a_host = rng.standard_normal((m, n)).astype(np.float32)
    b_host = (a_host @ x_true).astype(np.float32)

    opt = optax.sgd(args.lr)
    sh = trainer.shardings(mesh)
    a = jax.device_put(jnp.asarray(a_host), sh["a"])
    b = jax.device_put(jnp.asarray(b_host), sh["b"])
    state = trainer.init_state(mesh, n, opt)
    step_fn = trainer.build_train_step(mesh, opt)

    if args.ckpt_dir:
        latest = checkpoint.latest_step_dir(args.ckpt_dir)
        if latest is not None:
            state = checkpoint.restore_state(latest, state)
            if distributed.is_main_process():
                print(f"resumed from {latest} at step {int(state.step)}")

    start = int(state.step)
    loss = None
    for i in range(start, args.steps):
        state, loss = step_fn(state, a, b)
        if args.ckpt_dir and (i + 1) % args.ckpt_every == 0:
            path = checkpoint.save_state(
                state, Path(args.ckpt_dir) / f"step_{i + 1}"
            )
            if distributed.is_main_process():
                print(f"step {i + 1}: loss={float(loss):.3e} ckpt={path}")
        elif (i + 1) % max(1, args.steps // 10) == 0:
            if distributed.is_main_process():
                print(f"step {i + 1}: loss={float(loss):.3e}")

    err = float(jnp.max(jnp.abs(state.x - jnp.asarray(x_true, state.x.dtype))))
    if distributed.is_main_process():
        final = float(loss) if loss is not None else float("nan")
        print(f"done: steps={int(state.step)} final_loss={final:.3e} "
              f"max|x-x_true|={err:.3e}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Validate and land a completed TPU capture — the checklist as code.

``scripts/watch_and_capture.sh`` → ``scripts/tpu_measure_all.py`` writes
the round's evidence (loop-protocol CSVs, the 65536² bf16 north-star
artifact, the VMEM roof, figures, study docs) but deliberately does not
commit or re-narrate it. This script runs the landing steps that
previously lived in a prose checklist, so capture day is one command and
zero forgotten steps:

1. **Artifact inventory** — every file the capture should have produced,
   present or named as missing.
2. **Data-quality gates** — ``tests/test_data_quality.py`` must pass with
   ZERO skips: a skip means a gate that should now be biting is dormant.
3. **North star** — ``BASELINE.json``'s ``blockwise_65536_bf16_hbm_sweep``
   entry is updated from the capture's ``BASELINE_65536_bf16.json``
   (status → published, measured GB/s filled in).
4. **README tables** — the per-size results tables (square + asymmetric
   regimes) are rendered from the committed rows
   (``scripts/results_table.py``) and spliced between the
   ``TPU_RESULTS_TABLE`` markers in BOTH ``README.md`` and its RU mirror
   ``README_RU.md`` (tables are language-neutral; captions translate).
5. **Summary** — what changed, what to `git add`, and what (if anything)
   still needs a human: retiring ``data/out/superseded/`` is offered via
   ``--retire-superseded`` because PARITY.md promises wholesale
   replacement of the quarantined rows, and deleting data should be an
   explicit choice.

Read-only by default: without ``--apply`` every step reports what it
WOULD do. ``--apply`` performs steps 3–4 (and honors
``--retire-superseded``).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import subprocess
import sys
from pathlib import Path

# MATVEC_REPO_ROOT lets tests rehearse the landing against a synthetic
# repo tree (artifacts, README, BASELINE) without touching the real ones;
# CODE (tests, scripts) always runs from the real checkout.
CODE_ROOT = Path(__file__).resolve().parent.parent
REPO = Path(os.environ.get("MATVEC_REPO_ROOT") or CODE_ROOT)
sys.path.insert(0, str(CODE_ROOT))

TABLE_START = "<!-- TPU_RESULTS_TABLE_START -->"
TABLE_END = "<!-- TPU_RESULTS_TABLE_END -->"
NORTH_STAR_KEY = "blockwise_65536_bf16_hbm_sweep"


def _inventory(data_out: Path) -> tuple[list[str], list[str]]:
    expected = {
        "loop-protocol extended CSV": data_out / "results_extended.csv",
        "VMEM roof": data_out / "vmem_roof.json",
        "north-star artifact": REPO / "BASELINE_65536_bf16.json",
        "TPU figures": REPO / "figures" / "tpu",
    }
    for strategy in ("rowwise", "colwise", "colwise_ring",
                     "colwise_ring_overlap", "colwise_a2a", "blockwise"):
        expected[f"{strategy} CSV"] = data_out / f"{strategy}.csv"
    present, missing = [], []
    for label, path in expected.items():
        try:
            shown = path.relative_to(REPO)
        except ValueError:  # absolute --data-root outside the repo
            shown = path
        (present if path.exists() else missing).append(f"{label} ({shown})")
    return present, missing


def _gates() -> tuple[bool, str]:
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_data_quality.py",
         "-q", "-rs"],
        cwd=CODE_ROOT, capture_output=True, text=True,
    )
    out = r.stdout.strip().splitlines()
    tail = "\n".join(out[-12:])
    ok = r.returncode == 0 and "skipped" not in (out[-1] if out else "")
    return ok, tail


def _update_north_star(apply: bool) -> str:
    artifact = REPO / "BASELINE_65536_bf16.json"
    payload = json.loads(artifact.read_text())
    if payload.get("unit") not in ("GB/s", "GBps", "gbps"):
        return f"north star: unexpected unit {payload.get('unit')!r} — not applied"
    gbps = float(payload["value"])
    baseline_file = REPO / "BASELINE.json"
    baseline = json.loads(baseline_file.read_text())
    entry = baseline["published"][NORTH_STAR_KEY]
    before = entry.get("status"), entry.get("best_measured_gbps")
    if not apply:
        return (f"north star: would set status=published, "
                f"best_measured_gbps={gbps} (now {before[0]}, {before[1]})")
    entry["status"] = "published"
    entry["best_measured_gbps"] = gbps
    entry["mapping_note"] = (
        f"Measured by the landed capture (BASELINE_65536_bf16.json): "
        f"{gbps} GB/s at 65536^2 bf16, blockwise, measure=loop. "
        "Wedge/history notes prior to landing: see git history of this "
        "entry."
    )
    baseline_file.write_text(json.dumps(baseline, indent=1) + "\n")
    return (f"north star: status {before[0]} -> published, "
            f"best_measured_gbps {before[1]} -> {gbps}")


def _render_table(
    data_root: Path, shape: str = "square", *, required: bool = True
) -> str | None:
    """The rendered per-size table for one regime, or None when the
    renderer's filters match no rows. ``required`` tables print the
    renderer's diagnostics and the caller treats None as a pre-write
    abort; optional ones (the asymmetric regime — legitimately absent
    when a capture wedged after the square sweep) report the absence
    calmly and the landing proceeds without them."""
    r = subprocess.run(
        [sys.executable, "scripts/results_table.py",
         "--data-root", str(data_root), "--shape", shape],
        cwd=CODE_ROOT, capture_output=True, text=True,
    )
    if r.returncode != 0:
        if required:
            print(f"results_table.py ({shape}) failed — dataset present "
                  "but its rows don't match the renderer's filters:")
            print((r.stdout + r.stderr).strip())
        else:
            print(f"no {shape}-regime rows — landing without that table")
        return None
    return r.stdout.strip()


_CAPTIONS = {
    "README.md": (
        "Per-size amortized loop-protocol times on the one v5e chip "
        "(fp32; rendered from the committed "
        "`data/out/results_extended.csv` by `scripts/results_table.py`)."
        " Square regime:",
        "Asymmetric regime (non-square sizes):",
    ),
    "README_RU.md": (
        "По-размерные времена amortized-протокола loop на одном чипе v5e "
        "(fp32; отрендерено из зафиксированного "
        "`data/out/results_extended.csv` скриптом "
        "`scripts/results_table.py`). Квадратный режим:",
        "Асимметричный режим (неквадратные размеры):",
    ),
}


def _splice_readme(
    square_md: str, asym_md: str | None, apply: bool,
    readme_name: str = "README.md",
) -> str:
    readme = REPO / readme_name
    text = readme.read_text()
    if TABLE_START not in text or TABLE_END not in text:
        return f"{readme_name}: table markers missing — not applied"
    square_caption, asym_caption = _CAPTIONS[readme_name]
    parts = [TABLE_START, square_caption, "", square_md]
    if asym_md is not None:
        # The asymmetric regime is a first-class reference deliverable
        # (its asymmetric_*.csv files, quirk Q10). Caption stays generic:
        # the renderer's asym filter is "non-square", and each table row
        # labels its own m×n. Tables are language-neutral, so the RU
        # mirror splices the same markdown under a translated caption.
        parts += ["", asym_caption, "", asym_md]
    parts.append(TABLE_END)
    block = "\n".join(parts)
    new = re.sub(
        re.escape(TABLE_START) + r".*?" + re.escape(TABLE_END),
        block.replace("\\", r"\\"), text, flags=re.S,
    )
    if not apply:
        n_rows = block.count("\n|") - 2 * (2 if asym_md is not None else 1)
        return (f"{readme_name}: would splice {n_rows} table rows "
                "between markers")
    readme.write_text(new)
    return f"{readme_name}: per-size tables spliced between markers"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-root", default="data")
    p.add_argument("--apply", action="store_true",
                   help="write BASELINE.json, README.md and README_RU.md "
                   "(default: report)")
    p.add_argument("--retire-superseded", action="store_true",
                   help="delete data/out/superseded/ (the capture's dataset "
                   "wholesale-replaces the quarantined rows)")
    args = p.parse_args(argv)
    data_out = REPO / args.data_root / "out"

    if args.data_root != "data":
        # The data-quality gates (tests/test_data_quality.py) read the
        # committed data/ tree unconditionally — landing from another root
        # would gate the WRONG dataset and let ungated rows reach
        # BASELINE.json and the README. Non-default roots are for
        # inspection only.
        print(f"--data-root {args.data_root}: landing requires the default "
              "root (the gates only gate data/out); move the capture there "
              "first")
        return 1

    present, missing = _inventory(data_out)
    print(f"artifacts present ({len(present)}):")
    for line in present:
        print(f"  + {line}")
    if missing:
        print(f"artifacts MISSING ({len(missing)}):")
        for line in missing:
            print(f"  - {line}")

    core_ready = (data_out / "results_extended.csv").exists()
    if not core_ready:
        print("\nno loop-protocol dataset at the top level — nothing to "
              "land; the watcher/capture has not completed")
        return 1

    ok, tail = _gates()
    print("\ndata-quality gates:", "PASS, zero skips" if ok else "NOT CLEAN")
    if not ok:
        print(tail)
        print("\ngates must pass with zero skips before landing — aborting")
        return 1

    # EVERY validation runs before ANY write — a failure must leave
    # nothing half-landed (north star published without its README table,
    # or vice versa).
    problems = []
    table_md = _render_table(REPO / args.data_root, "square")
    if table_md is None:
        problems.append("dataset rows don't render (see above)")
    # The asymmetric table is included when its rows exist; a capture that
    # wedged after the square sweep still lands with the square table
    # alone (per-stage flushing means partial datasets are expected).
    asym_md = _render_table(REPO / args.data_root, "asym", required=False)
    # _CAPTIONS is the single list of localized READMEs: the pre-check,
    # the splice loop below, and the caption table cannot drift apart.
    for name in _CAPTIONS:
        readme_path = REPO / name
        if not readme_path.exists():
            problems.append(f"{name} missing")
            continue
        readme_text = readme_path.read_text()
        if TABLE_START not in readme_text or TABLE_END not in readme_text:
            problems.append(f"{name} TPU_RESULTS_TABLE markers missing")
    have_north_star = (REPO / "BASELINE_65536_bf16.json").exists()
    if have_north_star:
        unit = json.loads(
            (REPO / "BASELINE_65536_bf16.json").read_text()
        ).get("unit")
        if unit not in ("GB/s", "GBps", "gbps"):
            problems.append(
                f"BASELINE_65536_bf16.json has unexpected unit {unit!r}"
            )
    if problems:
        for prob in problems:
            print(f"pre-write check failed: {prob}")
        print("aborting before any write")
        return 1

    if have_north_star:
        print("\n" + _update_north_star(args.apply))
    else:
        print("\nnorth star: BASELINE_65536_bf16.json absent (baseline "
              "stage did not land) — BASELINE.json left untouched")

    for name in _CAPTIONS:
        print(_splice_readme(table_md, asym_md, args.apply, name))

    superseded = data_out / "superseded"
    if superseded.exists():
        if args.retire_superseded and args.apply:
            shutil.rmtree(superseded)
            print("retired data/out/superseded/ (use `git rm -r` to stage "
                  "the deletion)")
        elif args.retire_superseded:
            print("data/out/superseded/: would delete (needs --apply — "
                  "report mode never writes)")
        else:
            print("data/out/superseded/ still present — retire with "
                  "--apply --retire-superseded once the new dataset is "
                  "committed")

    if args.apply:
        # Landing completes the round: re-arm the capture's once-per-round
        # stale-CSV wipe (tpu_measure_all.py::_wipe_stale_csvs) so the NEXT
        # round's capture retires this round's rows instead of resuming
        # over a landed dataset under a possibly-changed protocol.
        sentinel = data_out / ".stale_wiped"
        if sentinel.exists():
            sentinel.unlink()
            print("cleared data/out/.stale_wiped — stale-CSV wipe re-armed "
                  "for the next round")
        print("\nsuggested staging:")
        print("  git add data/out/*.csv data/out/vmem_roof.json "
              "figures/tpu docs README.md README_RU.md BASELINE.json "
              "BASELINE_65536_bf16.json stats_visualization.ipynb")
        print("then run `python bench.py` once for the round's headline; "
              "both READMEs' tables are already spliced — check the "
              "surrounding RU prose still reads correctly")
    else:
        print("\n(report only — rerun with --apply to write)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

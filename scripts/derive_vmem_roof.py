#!/usr/bin/env python
"""Derive the sub-VMEM sanity ceiling from committed loop-measure rows.

``tests/test_data_quality.py`` bounds small-operand (VMEM-resident) TPU rows
by a sanity ceiling. Before any trusted on-chip measurement exists that
ceiling is a generous flat 5 TB/s — enough to catch clamp artifacts
(10^5-10^6 "GB/s") but loose enough that dispatch-jitter garbage under it
would pass (round-3 review, "what's weak" #2). This script replaces the
flat constant with a measurement-derived one, as a capture stage: read the
freshly-captured ``measure=loop`` rows, take the fastest *sub-VMEM*
bandwidth actually measured on the chip, and write
``data/out/vmem_roof.json`` holding that maximum plus the derived ceiling
(max × a documented head-room factor). The data-quality gate uses the
derived ceiling whenever the file exists, so the bound tightens from
5 TB/s to ~1.5× the best physically-measured value the moment a capture
lands — small-size garbage can no longer hide under the flat bound.

Wedge-safe: reads CSVs only, never touches the backend.

Usage: python scripts/derive_vmem_roof.py [--data-root data] [--min-rows 3]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from matvec_mpi_multiplier_tpu.utils.constants import (  # noqa: E402
    DTYPE_ITEMSIZE as ITEMSIZE,
    VMEM_BYTES,
)

# Head room over the fastest measured sub-VMEM row: tolerates run-to-run
# variance and modestly faster future configs without re-derivation, while
# staying ~3x tighter than the flat 5 TB/s for any plausible measurement.
HEADROOM = 1.5


def derive(data_root: Path, min_rows: int = 3) -> dict | None:
    from matvec_mpi_multiplier_tpu.bench.metrics import read_csv

    ext = data_root / "out" / "results_extended.csv"
    if not ext.exists():
        return None
    rows = [
        r for r in read_csv(ext)
        if r["measure"] == "loop"
        and ITEMSIZE[r["dtype"]] * r["n_rows"] * r["n_cols"] / r["n_devices"]
        <= VMEM_BYTES
    ]
    if len(rows) < min_rows:
        return None
    best = max(rows, key=lambda r: r["gbps"] / r["n_devices"])
    per_chip = best["gbps"] / best["n_devices"]
    return {
        "measured_max_per_chip_gbps": per_chip,
        "ceiling_per_chip_gbps": per_chip * HEADROOM,
        "headroom_factor": HEADROOM,
        "n_subvmem_loop_rows": len(rows),
        "source_row": {
            k: best[k]
            for k in ("strategy", "n_rows", "n_cols", "n_devices", "dtype",
                      "gbps")
        },
        "derivation": (
            "max over committed measure=loop rows with per-chip operand "
            f"bytes <= {VMEM_BYTES} of (gbps / n_devices), times "
            f"{HEADROOM} head room; consumed by tests/test_data_quality.py "
            "in place of the flat pre-measurement 5 TB/s sanity bound"
        ),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-root", default="data")
    p.add_argument(
        "--min-rows", type=int, default=3,
        help="refuse to derive a roof from fewer sub-VMEM loop rows than "
        "this (one stray row must not set the gate for the whole dataset)",
    )
    args = p.parse_args(argv)
    data_root = Path(args.data_root)
    payload = derive(data_root, args.min_rows)
    if payload is None:
        print(
            "no roof derived: need at least "
            f"{args.min_rows} sub-VMEM measure=loop rows in "
            f"{data_root / 'out' / 'results_extended.csv'}",
        )
        return 0  # not a capture failure: the gate just keeps the flat bound
    out = data_root / "out" / "vmem_roof.json"
    out.write_text(json.dumps(payload, indent=2) + "\n")
    print(
        f"wrote {out}: ceiling "
        f"{payload['ceiling_per_chip_gbps']:.1f} GB/s/chip "
        f"(= {HEADROOM} x measured "
        f"{payload['measured_max_per_chip_gbps']:.1f} from "
        f"{payload['n_subvmem_loop_rows']} rows)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
